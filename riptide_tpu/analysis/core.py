"""
riplint framework core: module contexts, findings, suppressions,
baseline handling and the runner loop shared by every analyzer.

Design constraints:

* importable WITHOUT jax or the riptide_tpu package __init__ (the
  runner loads the analysis package standalone by file path, so
  ``make check`` works on a box with no backend);
* one ``ast.parse`` per module, shared by all analyzers;
* suppression is explicit and reviewable — either an inline
  ``# riplint: disable=RIPxxx`` pragma on the flagged line, or a
  baseline entry in ``tools/riplint_baseline.json`` carrying a
  one-line justification. Baseline entries match on (rule, path,
  stripped source-line text), so they survive unrelated line moves but
  die with the code they describe — a stale entry fails the run.
"""
import ast
import json
import os
import re
from dataclasses import dataclass, field

__all__ = [
    "Analyzer", "Baseline", "Finding", "FunctionInfo", "ModuleContext",
    "ProjectContext", "collect_contexts", "run_analyzers",
]

# How far the baseline's staleness check looks around a finding for an
# entry's line text (see Baseline.matches): an unrelated same-file edit
# that reflows a wrapped statement moves the flagged line a little
# without changing the code the entry justified.
BASELINE_NEARBY_LINES = 3


@dataclass
class Finding:
    """One rule violation at a source location (1-based line, 0-based
    column, GitHub-annotation rendering)."""

    path: str      # repo-relative, forward slashes
    line: int
    col: int
    rule: str
    message: str

    def gh(self):
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"

    @classmethod
    def at(cls, ctx, node, rule, message):
        return cls(ctx.relpath, getattr(node, "lineno", 1),
                   getattr(node, "col_offset", 0), rule, message)


class ModuleContext:
    """One parsed module: path, source, lines and AST, shared by every
    analyzer (parse once)."""

    def __init__(self, repo, relpath):
        self.repo = repo
        self.relpath = relpath.replace(os.sep, "/")
        self.path = os.path.join(repo, relpath)
        with open(self.path) as fobj:
            self.source = fobj.read()
        self.lines = self.source.splitlines()
        self.tree = ast.parse(self.source, filename=self.path)

    def line_text(self, lineno):
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""


class Analyzer:
    """Base analyzer: subclass, set ``rule``/``name``/``description``,
    implement :meth:`run` (per module) and optionally :meth:`finalize`
    (whole-package checks, after every module ran). Analyzers that set
    ``needs_project = True`` additionally receive the shared
    :class:`ProjectContext` (name-resolved call graph) through
    :meth:`run_project` — built once per run, lazily, so per-file
    analyzers never pay for it."""

    rule = None
    name = None
    description = ""
    needs_project = False

    def begin(self, repo):
        """Reset per-run state. Called by :func:`run_analyzers` before
        the module sweep so a reused *instance* (tests pass instances
        to inject config) cannot leak accumulated state — e.g. a
        wrapped-call counter — from a previous run into a later one."""

    def run(self, ctx):
        """Findings for one :class:`ModuleContext`."""
        return []

    def run_project(self, project):
        """Findings from the whole-program view (only called when
        ``needs_project`` is set)."""
        return []

    def finalize(self, repo, contexts):
        """Findings that need the whole package (vacuous-lint guards,
        registry staleness, docs drift)."""
        return []


_PRAGMA = re.compile(r"#\s*riplint:\s*disable=([A-Za-z0-9_,\s]*)")


def is_suppressed(finding, ctx):
    """True when the flagged line carries an inline
    ``# riplint: disable=RIPxxx[,RIPyyy]`` (or ``disable=all``)
    pragma."""
    m = _PRAGMA.search(ctx.line_text(finding.line))
    if not m:
        return False
    rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
    return "all" in rules or finding.rule in rules


class Baseline:
    """Checked-in allowlist of intentional findings.

    JSON schema: ``{"entries": [{"rule", "path", "line_text", "why"},
    ...]}``. A finding is baselined when an entry's (rule, path,
    stripped line_text) matches it; entries that match nothing are
    STALE and fail the run (the code they justified is gone — delete
    or update them)."""

    def __init__(self, entries=(), path=None):
        self.entries = [dict(e) for e in entries]
        self.path = path
        self._used = [False] * len(self.entries)

    @classmethod
    def load(cls, path):
        if not os.path.exists(path):
            return cls(path=path)
        with open(path) as fobj:
            data = json.load(fobj)
        entries = data.get("entries", [])
        for e in entries:
            for k in ("rule", "path", "line_text", "why"):
                if k not in e:
                    raise ValueError(
                        f"{path}: baseline entry missing {k!r}: {e}"
                    )
        return cls(entries, path=path)

    def matches(self, finding, ctx):
        """True when an entry exactly matches ``finding``'s (rule,
        path, stripped line text at the finding's line)."""
        text = ctx.line_text(finding.line).strip()
        hit = False
        for i, e in enumerate(self.entries):
            if (e["rule"] == finding.rule and e["path"] == finding.path
                    and e["line_text"].strip() == text):
                self._used[i] = True
                hit = True
        return hit

    def matches_nearby(self, finding, ctx):
        """Reflow fallback, tried only AFTER every finding had its
        exact-match chance: an otherwise-UNUSED entry absorbs the
        finding when (a) its text survives within
        ``BASELINE_NEARBY_LINES`` of it (same rule + path) AND (b) the
        finding's own line text is a fragment of the entry's (or vice
        versa) — a wrapped statement's flagged line shifts a little
        under an unrelated reflow, but the flagged fragment still
        belongs to the justified statement. Both restrictions exist to
        keep the fuzz from swallowing a genuinely NEW violation that
        merely lands near a baselined one (its text is unrelated to
        the entry's, and the baselined line's own exact match marks
        the entry used)."""
        ftext = ctx.line_text(finding.line).strip()
        hit = False
        for i, e in enumerate(self.entries):
            if self._used[i] or e["rule"] != finding.rule \
                    or e["path"] != finding.path:
                continue
            etext = e["line_text"].strip()
            if not etext or not ftext:
                continue
            if ftext not in etext and etext not in ftext:
                continue
            if any(
                ctx.line_text(n).strip() == etext
                for n in range(finding.line - BASELINE_NEARBY_LINES,
                               finding.line + BASELINE_NEARBY_LINES + 1)
            ):
                self._used[i] = True
                hit = True
                break
        return hit

    def matches_pathonly(self, finding):
        """Match for findings outside the package (no ModuleContext,
        e.g. docs drift): an entry with an empty line_text on the same
        (rule, path). Marks the entry used so it does not read as
        stale."""
        hit = False
        for i, e in enumerate(self.entries):
            if (e["rule"] == finding.rule and e["path"] == finding.path
                    and e["line_text"].strip() == ""):
                self._used[i] = True
                hit = True
        return hit

    def stale_entries(self):
        return [e for i, e in enumerate(self.entries) if not self._used[i]]

    @staticmethod
    def entry_for(finding, ctx, why="TODO: justify"):
        return {
            "rule": finding.rule,
            "path": finding.path,
            "line_text": ctx.line_text(finding.line).strip(),
            "why": why,
        }

    def dump(self, path=None):
        path = path or self.path
        with open(path, "w") as fobj:
            json.dump({"entries": self.entries}, fobj, indent=2,
                      sort_keys=False)
            fobj.write("\n")


def collect_contexts(repo, package="riptide_tpu"):
    """Parsed :class:`ModuleContext` for every ``.py`` module under
    ``repo/package``, in stable path order."""
    contexts = []
    root = os.path.join(repo, package)
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fname in sorted(filenames):
            if fname.endswith(".py"):
                rel = os.path.relpath(os.path.join(dirpath, fname), repo)
                contexts.append(ModuleContext(repo, rel))
    return contexts


def run_analyzers(repo, analyzers, baseline=None, contexts=None):
    """Run every analyzer over the package.

    Returns ``(new, baselined, stale)``: findings not covered by pragma
    or baseline, findings absorbed by the baseline, and stale baseline
    entries. ``analyzers`` holds classes or instances."""
    if contexts is None:
        contexts = collect_contexts(repo)
    baseline = baseline or Baseline()
    instances = [a() if isinstance(a, type) else a for a in analyzers]
    by_rel = {c.relpath: c for c in contexts}

    # The whole-program view is shared (and lazy): one call-graph build
    # feeds every project-level analyzer of the run.
    project = None

    pending = []
    for inst in instances:
        inst.begin(repo)
        found = []
        for ctx in contexts:
            found.extend(inst.run(ctx))
        if getattr(inst, "needs_project", False):
            if project is None:
                project = ProjectContext(repo, contexts)
            found.extend(inst.run_project(project))
        found.extend(inst.finalize(repo, contexts))
        for f in found:
            ctx = by_rel.get(f.path)
            if ctx is None or not is_suppressed(f, ctx):
                pending.append((f, ctx))

    # Exact baseline matching first for EVERY finding, then the
    # nearby-lines reflow fallback for the leftovers — the order is
    # what lets matches_nearby restrict itself to unused entries.
    new, baselined, leftover = [], [], []
    for f, ctx in pending:
        if ctx is not None and baseline.matches(f, ctx):
            baselined.append(f)
        # Findings outside the package (e.g. docs drift) can only be
        # baselined with an empty line_text match.
        elif ctx is None and baseline.matches_pathonly(f):
            baselined.append(f)
        else:
            leftover.append((f, ctx))
    for f, ctx in leftover:
        if ctx is not None and baseline.matches_nearby(f, ctx):
            baselined.append(f)
        else:
            new.append(f)
    new.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return new, baselined, baseline.stale_entries()


# -- small shared AST helpers -----------------------------------------------

def dotted(node):
    """Dotted-name string of a Name/Attribute chain (``jax.jit`` ->
    "jax.jit"), or None for anything else."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(node):
    """Dotted name of a call's callee, or None."""
    if isinstance(node, ast.Call):
        return dotted(node.func)
    return None


def walk_functions(tree):
    """Yield every (async) function/method node with its qualified
    name ("Class.method" for methods)."""

    def visit(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{child.name}"
                yield qual, child
                yield from visit(child, f"{qual}.")
            elif isinstance(child, ast.ClassDef):
                yield from visit(child, f"{prefix}{child.name}.")
            else:
                yield from visit(child, prefix)

    yield from visit(tree, "")


def walk_own(fn):
    """Walk a function's body WITHOUT descending into nested (async)
    def bodies: those are separate :func:`walk_functions` entries (and
    separate :class:`FunctionInfo` nodes) whose code is deferred — a
    call made inside a nested def must not be attributed to the
    enclosing function's own execution. Lambda bodies ARE descended
    into: they have no FunctionInfo of their own, and the package's
    lambdas are invoked by the combinator they are handed to in the
    same dynamic context (jax control-flow tracing, executor.map)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


# -- whole-program call graph ------------------------------------------------

@dataclass
class FunctionInfo:
    """One function in the project call graph.

    ``fqn`` is ``"<relpath>::<qualname>"`` ("Class.method" quals for
    methods); ``calls`` holds ``(call_node, callee_fqn, kind)`` for
    every resolved outgoing edge, where ``kind`` is ``"call"`` for a
    plain invocation and ``"thread"`` for a target handed to another
    thread of execution (``Thread(target=...)``, ``executor.submit``) —
    thread edges transfer *reachability* but not held locks or an
    enclosing trace context."""

    fqn: str
    relpath: str
    qual: str
    node: object
    calls: list = field(default_factory=list)


def _module_name(relpath):
    """Dotted module name of a package-relative path
    ("riptide_tpu/survey/journal.py" -> "riptide_tpu.survey.journal")."""
    rel = relpath[:-3] if relpath.endswith(".py") else relpath
    parts = rel.split("/")
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


class ProjectContext:
    """Whole-program view over one run's :class:`ModuleContext` set: a
    **name-resolved call graph** built from one extra pass, shared by
    every project-level analyzer.

    Resolution is deliberately conservative — an edge exists only when
    the callee is identified through an explicit binding, never by
    leaf-name coincidence:

    * module-level functions, by definition or ``import``/
      ``from ... import`` binding (relative imports resolved against
      the importing module's package position);
    * methods via ``self`` — ``self.meth()`` resolves within the
      enclosing class, and ``self.attr.meth()`` through the class's
      **self-attribute types** (``self.attr = SomeClass(...)``
      assignments anywhere in the class);
    * constructor calls (``SomeClass(...)`` -> ``SomeClass.__init__``),
      including through module-level instances (``_default =
      Registry()`` makes ``_default.add()`` resolve) and single-step
      local bindings (``x = SomeClass(...)`` then ``x.meth()``);
    * one level of **return-type inference**: a function whose returns
      are all a known class's constructor call (or a module variable of
      known class) types its call results, so ``get_metrics().add()``
      resolves to ``MetricsRegistry.add``;
    * thread targets: ``threading.Thread(target=f)`` and
      ``executor.submit(f, ...)`` add a ``"thread"``-kind edge to the
      resolved target.

    Unresolvable calls (dynamic dispatch, parameters of unknown type,
    stdlib/third-party callees) simply contribute no edge — analyzers
    on top of this graph trade recall for zero-alias precision.
    """

    def __init__(self, repo, contexts):
        self.repo = repo
        self.contexts = list(contexts)
        self.by_rel = {c.relpath: c for c in self.contexts}
        self.functions = {}      # fqn -> FunctionInfo
        self.classes = {}        # (relpath, class) -> {method names}
        self.attr_types = {}     # (relpath, class, attr) -> (relpath2, class2)
        self.var_types = {}      # (relpath, module var) -> (relpath2, class2)
        self.return_types = {}   # fqn -> (relpath2, class2)
        self._imports = {}       # relpath -> {local name: binding}
        self._modnames = {_module_name(c.relpath): c.relpath
                          for c in self.contexts}
        self._callee_by_node = {}
        self._collect_definitions()
        self._collect_imports()
        self._collect_types()
        self._resolve_calls()

    # -- construction passes ------------------------------------------------

    def _collect_definitions(self):
        for ctx in self.contexts:
            for qual, fn in walk_functions(ctx.tree):
                fqn = f"{ctx.relpath}::{qual}"
                self.functions[fqn] = FunctionInfo(fqn, ctx.relpath, qual,
                                                   fn)
                if "." in qual:
                    cls, meth = qual.rsplit(".", 1)
                    if "." not in cls:  # only top-level classes
                        self.classes.setdefault(
                            (ctx.relpath, cls), set()).add(meth)
            for node in ctx.tree.body:
                if isinstance(node, ast.ClassDef):
                    self.classes.setdefault((ctx.relpath, node.name), set())

    def _collect_imports(self):
        """Per-module binding table: local name -> ("module", relpath)
        or ("symbol", relpath, original name). Function-local imports
        (the deferred cycle-breaking idiom) are folded into the same
        table — a per-function table isn't worth its weight — but
        module-level (tree.body) imports are applied LAST so they win
        any name conflict: a deferred import may add bindings, never
        shadow the module's own."""
        for ctx in self.contexts:
            table = self._imports.setdefault(ctx.relpath, {})
            own_mod = _module_name(ctx.relpath)
            top = set(map(id, ctx.tree.body))
            nodes = sorted(
                (n for n in ast.walk(ctx.tree)
                 if isinstance(n, (ast.Import, ast.ImportFrom))),
                key=lambda n: id(n) in top,
            )
            for node in nodes:
                if isinstance(node, ast.Import):
                    for a in node.names:
                        rel = self._modnames.get(a.name)
                        # `import a.b.c` (no asname) binds only the
                        # top-level name `a` in Python — binding it to
                        # the deepest module would resolve `a.<sym>`
                        # against the wrong namespace, so only the
                        # asname and single-component forms enter the
                        # table.
                        if rel and (a.asname or "." not in a.name):
                            table[a.asname or a.name] = ("module", rel)
                elif isinstance(node, ast.ImportFrom):
                    base = node.module or ""
                    if node.level:
                        parts = own_mod.split(".")
                        # level 1 = the containing package. For a
                        # plain module file that strips its own name;
                        # an __init__.py's dotted name already IS the
                        # package, so it strips one component fewer.
                        strip = node.level
                        if ctx.relpath.endswith("__init__.py"):
                            strip -= 1
                        if strip:
                            parts = parts[: len(parts) - strip]
                        base = ".".join(parts + ([base] if base else []))
                    for a in node.names:
                        local = a.asname or a.name
                        as_mod = self._modnames.get(
                            f"{base}.{a.name}" if base else a.name)
                        if as_mod:
                            table[local] = ("module", as_mod)
                            continue
                        rel = self._modnames.get(base)
                        if rel:
                            table[local] = ("symbol", rel, a.name)

    def _class_of_value(self, relpath, value):
        """(relpath, class) a value expression constructs, or None.
        Follows ``A or B`` to its last operand (the ``metrics or
        get_metrics()`` default idiom) and call results through
        :attr:`return_types`."""
        if isinstance(value, ast.BoolOp) and value.values:
            return self._class_of_value(relpath, value.values[-1])
        if not isinstance(value, ast.Call):
            return None
        name = dotted(value.func)
        if name is None:
            return None
        target = self._lookup(relpath, name)
        if target is None:
            return None
        kind, payload = target
        if kind == "class":
            return payload
        if kind == "function":
            return self.return_types.get(payload)
        return None

    def _collect_types(self):
        """Self-attribute, module-variable and return types (fixpoint:
        return types can depend on module-variable types and vice
        versa; two passes reach the repo's depth-one idioms)."""
        for _ in range(2):
            for ctx in self.contexts:
                # Module-level instances.
                for node in ctx.tree.body:
                    if isinstance(node, ast.Assign) \
                            and len(node.targets) == 1 \
                            and isinstance(node.targets[0], ast.Name):
                        typ = self._class_of_value(ctx.relpath, node.value)
                        if typ:
                            self.var_types[
                                (ctx.relpath, node.targets[0].id)] = typ
                for qual, fn in walk_functions(ctx.tree):
                    if "." in qual:
                        cls = qual.split(".")[0]
                        for sub in ast.walk(fn):
                            if isinstance(sub, ast.Assign) \
                                    and len(sub.targets) == 1:
                                t = sub.targets[0]
                                if isinstance(t, ast.Attribute) \
                                        and isinstance(t.value, ast.Name) \
                                        and t.value.id == "self":
                                    typ = self._class_of_value(ctx.relpath,
                                                               sub.value)
                                    if typ:
                                        self.attr_types[
                                            (ctx.relpath, cls, t.attr)] = typ
                    # Return type: every return returns the same class.
                    types = set()
                    opaque = False
                    for sub in ast.walk(fn):
                        if isinstance(sub, ast.Return) \
                                and sub.value is not None:
                            typ = None
                            if isinstance(sub.value, ast.Name):
                                typ = self.var_types.get(
                                    (ctx.relpath, sub.value.id))
                            else:
                                typ = self._class_of_value(ctx.relpath,
                                                           sub.value)
                            if typ is None:
                                opaque = True
                            else:
                                types.add(typ)
                    if not opaque and len(types) == 1:
                        self.return_types[f"{ctx.relpath}::{qual}"] = \
                            types.pop()

    def _lookup(self, relpath, name):
        """Resolve a dotted name in a module's namespace to
        ``("function", fqn)`` or ``("class", (relpath, class))``."""
        parts = name.split(".")
        table = self._imports.get(relpath, {})
        head, rest = parts[0], parts[1:]

        def in_module(rel, sym_parts):
            qual = ".".join(sym_parts)
            if f"{rel}::{qual}" in self.functions:
                return ("function", f"{rel}::{qual}")
            if len(sym_parts) == 1 and (rel, sym_parts[0]) in self.classes:
                return ("class", (rel, sym_parts[0]))
            if len(sym_parts) >= 1:
                typ = self.var_types.get((rel, sym_parts[0]))
                if typ and len(sym_parts) == 2:
                    trel, tcls = typ
                    if f"{trel}::{tcls}.{sym_parts[1]}" in self.functions:
                        return ("function",
                                f"{trel}::{tcls}.{sym_parts[1]}")
            return None

        binding = table.get(head)
        if binding is not None:
            if binding[0] == "module":
                return in_module(binding[1], rest) if rest else None
            _, rel, orig = binding
            return in_module(rel, [orig] + rest)
        return in_module(relpath, parts)

    def _resolve_callable_ref(self, relpath, owner_class, local_types,
                              node):
        """Resolve a *reference* expression (a thread target, a submit
        argument) to a function fqn, or None."""
        name = dotted(node)
        if name is None:
            return None
        return self._resolve_name(relpath, owner_class, local_types,
                                  name, as_ref=True,
                                  lineno=getattr(node, "lineno", None))

    def _resolve_name(self, relpath, owner_class, local_types, name,
                      as_ref=False, lineno=None):
        """Resolve a dotted callee name to a function fqn (constructor
        calls land on ``__init__`` unless ``as_ref``)."""
        parts = name.split(".")

        def method_of(typ, meth):
            if typ is None:
                return None
            trel, tcls = typ
            fqn = f"{trel}::{tcls}.{meth}"
            return fqn if fqn in self.functions else None

        if parts[0] == "self" and owner_class is not None:
            if len(parts) == 2:
                return method_of((relpath, owner_class), parts[1])
            if len(parts) == 3:
                typ = self.attr_types.get(
                    (relpath, owner_class, parts[1]))
                return method_of(typ, parts[2])
            return None
        if parts[0] in local_types:
            typ, bind_line = local_types[parts[0]]
            # A local binding only types uses at or after it.
            if lineno is not None and lineno < bind_line:
                return None
            if len(parts) == 2:
                return method_of(typ, parts[1])
            return None
        resolved = self._lookup(relpath, name)
        if resolved is None:
            # A method on a module-level instance of another module
            # (`journal._default.heartbeat()`), already covered by
            # _lookup's var_types branch; nothing more to try.
            return None
        kind, payload = resolved
        if kind == "function":
            return payload
        if kind == "class" and not as_ref:
            return method_of(payload, "__init__")
        return None

    def _resolve_calls(self):
        for info in self.functions.values():
            owner = info.qual.split(".")[0] if "." in info.qual else None
            ctx_rel = info.relpath
            # Single-step local constructor bindings (x = SomeClass()):
            # only names bound EXACTLY once in the function (any other
            # store — rebinding, loop target, unpacking — disqualifies)
            # and never parameters, so a binding cannot type a use it
            # does not dominate; uses before the binding line are
            # additionally rejected at resolution time.
            params = {a.arg for a in ast.walk(info.node.args)
                      if isinstance(a, ast.arg)}
            store_counts = {}
            for sub in walk_own(info.node):
                if isinstance(sub, ast.Name) \
                        and isinstance(sub.ctx, ast.Store):
                    store_counts[sub.id] = store_counts.get(sub.id,
                                                            0) + 1
            local_types = {}   # name -> ((relpath, class), bind line)
            for sub in walk_own(info.node):
                if isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                        and isinstance(sub.targets[0], ast.Name):
                    name = sub.targets[0].id
                    if store_counts.get(name) != 1 or name in params:
                        continue
                    typ = self._class_of_value(ctx_rel, sub.value)
                    if typ:
                        local_types[name] = (typ, sub.lineno)
            for sub in walk_own(info.node):
                if not isinstance(sub, ast.Call):
                    continue
                callee = None
                name = dotted(sub.func)
                if name is not None:
                    callee = self._resolve_name(
                        ctx_rel, owner, local_types, name,
                        lineno=sub.lineno)
                elif isinstance(sub.func, ast.Attribute) \
                        and isinstance(sub.func.value, ast.Call):
                    # f(...).meth(): type the inner call's result.
                    typ = self._class_of_value(ctx_rel, sub.func.value)
                    if typ:
                        trel, tcls = typ
                        fqn = f"{trel}::{tcls}.{sub.func.attr}"
                        callee = fqn if fqn in self.functions else None
                if callee is not None:
                    info.calls.append((sub, callee, "call"))
                    self._callee_by_node[id(sub)] = callee
                # Thread-of-execution handoffs.
                leaf = (name or "").split(".")[-1]
                target = None
                if leaf == "Thread":
                    for kw in sub.keywords:
                        if kw.arg == "target":
                            target = kw.value
                elif leaf == "submit" and sub.args:
                    target = sub.args[0]
                if target is not None:
                    tgt = self._resolve_callable_ref(ctx_rel, owner,
                                                     local_types, target)
                    if tgt is not None:
                        info.calls.append((sub, tgt, "thread"))

    # -- queries ------------------------------------------------------------

    def callee(self, node):
        """The resolved ``"call"``-kind callee fqn of a Call node seen
        during graph construction, or None."""
        return self._callee_by_node.get(id(node))

    def context_of(self, fqn):
        """The :class:`ModuleContext` holding ``fqn``."""
        return self.by_rel[self.functions[fqn].relpath]

    def reachable(self, roots, kinds=("call",)):
        """``{fqn: (parent fqn or None)}`` for every function reachable
        from ``roots`` over edges of the given kinds — the parent map
        doubles as the witness path for diagnostics."""
        parents = {}
        frontier = []
        for r in roots:
            if r in self.functions and r not in parents:
                parents[r] = None
                frontier.append(r)
        while frontier:
            cur = frontier.pop()
            for _, callee, kind in self.functions[cur].calls:
                if kind in kinds and callee not in parents:
                    parents[callee] = cur
                    frontier.append(callee)
        return parents

    def witness_path(self, parents, fqn):
        """Root-to-``fqn`` chain of quals through a :meth:`reachable`
        parent map (for "reachable via ..." messages)."""
        chain = []
        cur = fqn
        while cur is not None:
            chain.append(self.functions[cur].qual)
            cur = parents.get(cur)
        return list(reversed(chain))
