"""
riplint framework core: module contexts, findings, suppressions,
baseline handling and the runner loop shared by every analyzer.

Design constraints:

* importable WITHOUT jax or the riptide_tpu package __init__ (the
  runner loads the analysis package standalone by file path, so
  ``make check`` works on a box with no backend);
* one ``ast.parse`` per module, shared by all analyzers;
* suppression is explicit and reviewable — either an inline
  ``# riplint: disable=RIPxxx`` pragma on the flagged line, or a
  baseline entry in ``tools/riplint_baseline.json`` carrying a
  one-line justification. Baseline entries match on (rule, path,
  stripped source-line text), so they survive unrelated line moves but
  die with the code they describe — a stale entry fails the run.
"""
import ast
import json
import os
import re
from dataclasses import dataclass, field

__all__ = [
    "Analyzer", "Baseline", "Finding", "ModuleContext",
    "collect_contexts", "run_analyzers",
]


@dataclass
class Finding:
    """One rule violation at a source location (1-based line, 0-based
    column, GitHub-annotation rendering)."""

    path: str      # repo-relative, forward slashes
    line: int
    col: int
    rule: str
    message: str

    def gh(self):
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"

    @classmethod
    def at(cls, ctx, node, rule, message):
        return cls(ctx.relpath, getattr(node, "lineno", 1),
                   getattr(node, "col_offset", 0), rule, message)


class ModuleContext:
    """One parsed module: path, source, lines and AST, shared by every
    analyzer (parse once)."""

    def __init__(self, repo, relpath):
        self.repo = repo
        self.relpath = relpath.replace(os.sep, "/")
        self.path = os.path.join(repo, relpath)
        with open(self.path) as fobj:
            self.source = fobj.read()
        self.lines = self.source.splitlines()
        self.tree = ast.parse(self.source, filename=self.path)

    def line_text(self, lineno):
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""


class Analyzer:
    """Base analyzer: subclass, set ``rule``/``name``/``description``,
    implement :meth:`run` (per module) and optionally :meth:`finalize`
    (whole-package checks, after every module ran)."""

    rule = None
    name = None
    description = ""

    def begin(self, repo):
        """Reset per-run state. Called by :func:`run_analyzers` before
        the module sweep so a reused *instance* (tests pass instances
        to inject config) cannot leak accumulated state — e.g. a
        wrapped-call counter — from a previous run into a later one."""

    def run(self, ctx):
        """Findings for one :class:`ModuleContext`."""
        return []

    def finalize(self, repo, contexts):
        """Findings that need the whole package (vacuous-lint guards,
        registry staleness, docs drift)."""
        return []


_PRAGMA = re.compile(r"#\s*riplint:\s*disable=([A-Za-z0-9_,\s]*)")


def is_suppressed(finding, ctx):
    """True when the flagged line carries an inline
    ``# riplint: disable=RIPxxx[,RIPyyy]`` (or ``disable=all``)
    pragma."""
    m = _PRAGMA.search(ctx.line_text(finding.line))
    if not m:
        return False
    rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
    return "all" in rules or finding.rule in rules


class Baseline:
    """Checked-in allowlist of intentional findings.

    JSON schema: ``{"entries": [{"rule", "path", "line_text", "why"},
    ...]}``. A finding is baselined when an entry's (rule, path,
    stripped line_text) matches it; entries that match nothing are
    STALE and fail the run (the code they justified is gone — delete
    or update them)."""

    def __init__(self, entries=(), path=None):
        self.entries = [dict(e) for e in entries]
        self.path = path
        self._used = [False] * len(self.entries)

    @classmethod
    def load(cls, path):
        if not os.path.exists(path):
            return cls(path=path)
        with open(path) as fobj:
            data = json.load(fobj)
        entries = data.get("entries", [])
        for e in entries:
            for k in ("rule", "path", "line_text", "why"):
                if k not in e:
                    raise ValueError(
                        f"{path}: baseline entry missing {k!r}: {e}"
                    )
        return cls(entries, path=path)

    def matches(self, finding, ctx):
        text = ctx.line_text(finding.line).strip()
        hit = False
        for i, e in enumerate(self.entries):
            if (e["rule"] == finding.rule and e["path"] == finding.path
                    and e["line_text"].strip() == text):
                self._used[i] = True
                hit = True
        return hit

    def matches_pathonly(self, finding):
        """Match for findings outside the package (no ModuleContext,
        e.g. docs drift): an entry with an empty line_text on the same
        (rule, path). Marks the entry used so it does not read as
        stale."""
        hit = False
        for i, e in enumerate(self.entries):
            if (e["rule"] == finding.rule and e["path"] == finding.path
                    and e["line_text"].strip() == ""):
                self._used[i] = True
                hit = True
        return hit

    def stale_entries(self):
        return [e for i, e in enumerate(self.entries) if not self._used[i]]

    @staticmethod
    def entry_for(finding, ctx, why="TODO: justify"):
        return {
            "rule": finding.rule,
            "path": finding.path,
            "line_text": ctx.line_text(finding.line).strip(),
            "why": why,
        }

    def dump(self, path=None):
        path = path or self.path
        with open(path, "w") as fobj:
            json.dump({"entries": self.entries}, fobj, indent=2,
                      sort_keys=False)
            fobj.write("\n")


def collect_contexts(repo, package="riptide_tpu"):
    """Parsed :class:`ModuleContext` for every ``.py`` module under
    ``repo/package``, in stable path order."""
    contexts = []
    root = os.path.join(repo, package)
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fname in sorted(filenames):
            if fname.endswith(".py"):
                rel = os.path.relpath(os.path.join(dirpath, fname), repo)
                contexts.append(ModuleContext(repo, rel))
    return contexts


def run_analyzers(repo, analyzers, baseline=None, contexts=None):
    """Run every analyzer over the package.

    Returns ``(new, baselined, stale)``: findings not covered by pragma
    or baseline, findings absorbed by the baseline, and stale baseline
    entries. ``analyzers`` holds classes or instances."""
    if contexts is None:
        contexts = collect_contexts(repo)
    baseline = baseline or Baseline()
    instances = [a() if isinstance(a, type) else a for a in analyzers]
    by_rel = {c.relpath: c for c in contexts}

    new, baselined = [], []
    for inst in instances:
        inst.begin(repo)
        found = []
        for ctx in contexts:
            found.extend(inst.run(ctx))
        found.extend(inst.finalize(repo, contexts))
        for f in found:
            ctx = by_rel.get(f.path)
            if ctx is not None and is_suppressed(f, ctx):
                continue
            if ctx is not None and baseline.matches(f, ctx):
                baselined.append(f)
                continue
            # Findings outside the package (e.g. docs drift) can only
            # be baselined with an empty line_text match.
            if ctx is None and baseline.matches_pathonly(f):
                baselined.append(f)
                continue
            new.append(f)
    new.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return new, baselined, baseline.stale_entries()


# -- small shared AST helpers -----------------------------------------------

def dotted(node):
    """Dotted-name string of a Name/Attribute chain (``jax.jit`` ->
    "jax.jit"), or None for anything else."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(node):
    """Dotted name of a call's callee, or None."""
    if isinstance(node, ast.Call):
        return dotted(node.func)
    return None


def walk_functions(tree):
    """Yield every (async) function/method node with its qualified
    name ("Class.method" for methods)."""

    def visit(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{child.name}"
                yield qual, child
                yield from visit(child, f"{qual}.")
            elif isinstance(child, ast.ClassDef):
                yield from visit(child, f"{prefix}{child.name}.")
            else:
                yield from visit(child, prefix)

    yield from visit(tree, "")
