"""
RIP011 — interprocedural host-sync: RIP001 lifted to call-graph
reachability.

RIP001 scans the *bodies* of jit-decorated functions and an explicit
hot-path list; a ``.item()`` moved one helper call deep passes it
clean while still forcing the same device round trip at trace time.
This analyzer walks the :class:`~riptide_tpu.analysis.core.
ProjectContext` call graph from every traced root —

* jit-decorated functions (``@jax.jit`` / ``partial(jax.jit, ...)`` /
  ``cached_jit``, the RIP001 detector), and
* Pallas kernel closures (the functions handed to ``pallas_call``,
  via RIP005's per-module root extraction);

— and flags the unambiguous sync pulls (``.item()`` / ``.tolist()`` /
``.block_until_ready()`` / ``jax.device_get`` / ``np.asarray``-family)
in every *reachable* helper, naming the root and the call chain so the
finding is actionable from the message alone. Roots themselves are
skipped (RIP001 already owns them — one defect, one rule), as are
``"thread"``-kind edges (a spawned thread is a new host context, not
traced code).

``float()``/``int()`` on non-literals is deliberately NOT lifted:
helpers shared between traced and host paths do legitimate host
arithmetic, and the cast check's precision comes from knowing it runs
at trace time — which only holds in the root's own body.
"""
import ast

from .core import Analyzer, Finding, dotted, walk_functions, walk_own
from .host_sync import _SYNC_ATTRS, _is_jit_decorated, _np_pull
from .pallas_layout import PallasLayoutAnalyzer

__all__ = ["InterpHostSyncAnalyzer"]


class InterpHostSyncAnalyzer(Analyzer):
    rule = "RIP011"
    name = "interp-host-sync"
    description = ("no host synchronisation anywhere reachable from a "
                   "jit body or Pallas kernel closure through the "
                   "project call graph")
    needs_project = True

    def run_project(self, project):
        roots = {}
        pallas = PallasLayoutAnalyzer()
        for ctx in project.contexts:
            kernel_roots = pallas._kernel_roots(ctx)
            for qual, fn in walk_functions(ctx.tree):
                fqn = f"{ctx.relpath}::{qual}"
                if _is_jit_decorated(fn):
                    roots[fqn] = "jit body"
                elif qual.split(".")[-1] in kernel_roots \
                        and ("." not in qual
                             or (ctx.relpath, qual.split(".")[0])
                             not in project.classes):
                    # Kernel roots are module-level (or builder-nested)
                    # functions; a class METHOD sharing the leaf name
                    # is host code, neither a root nor exempt.
                    roots[fqn] = "Pallas kernel closure"

        parents = project.reachable(roots, kinds=("call",))
        findings = []
        for fqn in sorted(parents):
            if fqn in roots:
                continue  # RIP001/RIP005 own the root bodies
            info = project.functions[fqn]
            ctx = project.context_of(fqn)
            chain = project.witness_path(parents, fqn)
            root_fqn = fqn
            while parents.get(root_fqn) is not None:
                root_fqn = parents[root_fqn]
            where = (f"`{info.qual}`, reachable from "
                     f"{roots[root_fqn]} `"
                     f"{project.functions[root_fqn].qual}` via "
                     + " -> ".join(chain))
            findings.extend(self._scan(ctx, info.node, where))
        findings.sort(key=lambda f: (f.path, f.line, f.col))
        return findings

    def _scan(self, ctx, fn, where):
        out = []
        # walk_own: a nested def inside a reachable helper is its own
        # FunctionInfo, flagged only if itself reachable.
        for node in walk_own(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in _SYNC_ATTRS \
                    and not node.args:
                out.append(Finding.at(
                    ctx, node, self.rule,
                    f"`.{f.attr}()` forces a device sync inside {where} "
                    "— the pull is invisible to RIP001's body scan but "
                    "still runs at trace time; hoist it to the collect "
                    "side or take the value as a static argument",
                ))
            elif (dotted(f) or "").endswith("device_get"):
                out.append(Finding.at(
                    ctx, node, self.rule,
                    f"`jax.device_get` inside {where} — a device->host "
                    "pull on a traced path",
                ))
            elif _np_pull(node):
                out.append(Finding.at(
                    ctx, node, self.rule,
                    f"`{dotted(f)}` inside {where} materialises its "
                    "argument on the host (a silent device sync when "
                    "fed a traced array)",
                ))
        return out
