"""
RIP003 — env-flag hygiene.

Every ``RIPTIDE_*`` environment read inside the package must resolve
through the typed registry (``riptide_tpu/utils/envflags.py``): one
place declares the name, type, default and documentation, so a typo'd
flag raises instead of silently doing nothing and the operator surface
is enumerable. The analyzer enforces three properties:

* **no raw reads** — ``os.environ`` / ``os.getenv`` access with a
  ``RIPTIDE_*`` key anywhere in ``riptide_tpu/`` except envflags.py
  itself;
* **no unknown flags** — every ``envflags.get(...)`` of a flag-name
  literal in package code must name a registered flag;
* **no stale entries** — every registry entry must still be read
  somewhere in the repo (package code, bench.py, tools/, tests/,
  Makefile); a flag nothing reads is dead configuration surface.

It also fails when ``docs/env_flags.md`` drifts from the registry's
``render_markdown()`` (regenerate with ``tools/riplint.py
--write-env-docs``).
"""
import ast
import importlib.util
import os
import re

from .core import Analyzer, Finding, dotted

__all__ = ["EnvFlagAnalyzer", "load_registry"]

REGISTRY_REL = "riptide_tpu/utils/envflags.py"
DOCS_REL = "docs/env_flags.md"

# Files outside the package whose direct RIPTIDE_* reads are legitimate
# (pre-jax entry points and test plumbing); they count as *usage* for
# the stale-entry check.
_EXTRA_USAGE = ("bench.py", "Makefile", "tools", "tests")

_TOKEN = re.compile(r"RIPTIDE_[A-Z0-9_]+")


def load_registry(repo):
    """The envflags module, loaded standalone by file path (no jax, no
    riptide_tpu/__init__)."""
    path = os.path.join(repo, REGISTRY_REL)
    spec = importlib.util.spec_from_file_location(
        "riptide_tpu_envflags_standalone", path
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _env_read_key(node):
    """The RIPTIDE_* key of a raw environment read, or None.

    Matches ``os.environ.get(K, ...)``, ``os.environ[K]``,
    ``os.environ.pop(K, ...)``, ``os.getenv(K, ...)`` and the same via
    ``environ`` imported bare."""
    key_node = None
    if isinstance(node, ast.Call):
        name = dotted(node.func) or ""
        if name in ("os.environ.get", "environ.get", "os.environ.pop",
                    "environ.pop", "os.environ.setdefault",
                    "environ.setdefault", "os.getenv", "getenv"):
            if node.args:
                key_node = node.args[0]
    elif isinstance(node, ast.Subscript):
        base = dotted(node.value) or ""
        if base in ("os.environ", "environ"):
            key_node = node.slice
            if isinstance(key_node, ast.Index):  # py3.8 compat
                key_node = key_node.value
    if isinstance(key_node, ast.Constant) and isinstance(key_node.value,
                                                         str):
        if key_node.value.startswith("RIPTIDE_"):
            return key_node.value
    return None


class EnvFlagAnalyzer(Analyzer):
    rule = "RIP003"
    name = "env-flags"
    description = ("every RIPTIDE_* read routes through the typed "
                   "utils/envflags.py registry; stale entries and docs "
                   "drift are errors")

    def run(self, ctx):
        if ctx.relpath == REGISTRY_REL:
            return []
        findings = []
        known = None
        for node in ast.walk(ctx.tree):
            key = _env_read_key(node)
            if key is not None:
                findings.append(Finding.at(
                    ctx, node, self.rule,
                    f"raw environment read of {key!r} — route it through "
                    "riptide_tpu.utils.envflags.get() so the flag is "
                    "typed, documented and enumerable",
                ))
                continue
            # envflags.get with an unregistered flag name.
            if isinstance(node, ast.Call):
                name = dotted(node.func) or ""
                if name.split(".")[-1] == "get" \
                        and "envflags" in name and node.args:
                    a = node.args[0]
                    if isinstance(a, ast.Constant) \
                            and isinstance(a.value, str) \
                            and a.value.startswith("RIPTIDE_"):
                        if known is None:
                            known = set(
                                load_registry(ctx.repo).FLAGS
                            )
                        if a.value not in known:
                            findings.append(Finding.at(
                                ctx, node, self.rule,
                                f"unregistered flag {a.value!r} — declare "
                                "it in riptide_tpu/utils/envflags.py "
                                "(envflags.get would raise KeyError at "
                                "runtime)",
                            ))
        return findings

    def finalize(self, repo, contexts):
        findings = []
        try:
            registry = load_registry(repo)
        except Exception as err:  # registry must always import clean
            return [Finding(REGISTRY_REL, 1, 0, self.rule,
                            f"failed to load the flag registry: {err}")]

        # -- stale-entry detection ------------------------------------
        usage = set()
        for ctx in contexts:
            if ctx.relpath != REGISTRY_REL:
                usage.update(_TOKEN.findall(ctx.source))
        for extra in _EXTRA_USAGE:
            path = os.path.join(repo, extra)
            files = []
            if os.path.isfile(path):
                files = [path]
            elif os.path.isdir(path):
                for dirpath, dirnames, filenames in os.walk(path):
                    dirnames[:] = [d for d in dirnames
                                   if d != "__pycache__"]
                    files.extend(os.path.join(dirpath, f)
                                 for f in filenames
                                 if f.endswith((".py", ".mk"))
                                 or f == "Makefile")
            for f in files:
                try:
                    with open(f, errors="replace") as fobj:
                        usage.update(_TOKEN.findall(fobj.read()))
                except OSError:
                    continue
        reg_src = open(os.path.join(repo, REGISTRY_REL)).read().splitlines()
        for name in registry.FLAGS:
            if name not in usage:
                line = next(
                    (i + 1 for i, t in enumerate(reg_src) if name in t), 1
                )
                findings.append(Finding(
                    REGISTRY_REL, line, 0, self.rule,
                    f"stale registry entry {name!r}: no read anywhere in "
                    "the repo — delete the entry or the dead flag's "
                    "documentation lies",
                ))

        # -- docs drift ------------------------------------------------
        docs_path = os.path.join(repo, DOCS_REL)
        want = registry.render_markdown()
        have = None
        if os.path.exists(docs_path):
            with open(docs_path) as fobj:
                have = fobj.read()
        if have != want:
            findings.append(Finding(
                DOCS_REL, 1, 0, self.rule,
                "docs/env_flags.md is out of sync with the envflags.py "
                "registry — regenerate with `python tools/riplint.py "
                "--write-env-docs`",
            ))
        return findings
