"""
RIP014 — gate/resource begin-end pairing on every path.

Three protocols in the survey/serve planes hand out a resource whose
release MUST happen on every control-flow path, or the system leaks
capacity until a hang (ripsched's ``fairshare``/``staging`` models
show the dynamic failure; this rule pins the static shape):

* ``chunk_gate.begin(cid)`` / ``.end(cid)`` — the fair-share queue's
  device turn. A missed ``end`` keeps the turn forever: every other
  job's ``begin`` parks until its deadline (the exact hang the
  drain-termination invariant guards).
* ``pool.acquire(...)`` / ``pool.release(buf)`` — the staging arena.
  A buffer that never returns shrinks the arena until prep stalls.
* ``integrity.begin_fold(...)`` / ``finish_fold(acc)`` — the
  integrity accumulator (matched by method name: its receiver
  varies).

A ``begin``/``acquire`` is compliant when a ``try`` whose
``finally`` holds the matching ``end``/``release`` (same pair, same
receiver name) covers it — including the repo's
begin-immediately-before-``try`` idiom — or, for ``acquire`` only,
when the result **escapes** the function (returned, or stored into an
attribute/subscript, directly or through local-name propagation):
ownership moved to the caller, release is its job. Receiver-name
sets keep unrelated ``begin``/``acquire`` protocols (chaos blockers,
HTTP handlers) out; like every riplint rule, a shape the resolver
cannot see contributes no finding.
"""
import ast

from .core import Analyzer, Finding, dotted, walk_functions, walk_own

__all__ = ["GatePairingAnalyzer", "PAIRS"]

# (open method, close method, receiver leaf-name set or None for
# match-by-method-name, result-escape exemption)
PAIRS = (
    ("begin", "end", frozenset({"chunk_gate", "gate"}), False),
    ("acquire", "release",
     frozenset({"pool", "_pool", "staging", "_staging", "staging_pool",
                "_staging_pool"}), True),
    ("begin_fold", "finish_fold", None, False),
)


def _receiver_leaf(func):
    """Leaf name of a method call's receiver: ``self.chunk_gate.begin``
    -> "chunk_gate", ``pool.acquire`` -> "pool"."""
    if not isinstance(func, ast.Attribute):
        return None
    recv = func.value
    if isinstance(recv, ast.Attribute):
        return recv.attr
    if isinstance(recv, ast.Name):
        return recv.id
    return None


def _method_calls(fn_node, method, receivers):
    """Call nodes of ``<recv>.<method>(...)`` in a function's own body
    (any receiver when ``receivers`` is None)."""
    for node in walk_own(fn_node):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == method:
            leaf = _receiver_leaf(node.func)
            if leaf is None:
                continue
            if receivers is None or leaf in receivers:
                yield node, leaf


def _flat_targets(targets):
    """Assignment target nodes with tuple/list structure flattened
    (``flat, scales = ...`` stores two Names)."""
    out = []
    stack = list(targets)
    while stack:
        t = stack.pop()
        if isinstance(t, (ast.Tuple, ast.List)):
            stack.extend(t.elts)
        elif isinstance(t, ast.Starred):
            stack.append(t.value)
        else:
            out.append(t)
    return out


def _escaped_names(fn_node):
    """Local names whose value escapes the function: returned, stored
    into an attribute/subscript, or assigned onward (including through
    a container literal or a call whose result is so stored — the
    ``out=`` buffer-filling idiom) to a name that escapes. Two
    propagation passes cover the repo's depth."""
    escaped = set()
    for _ in range(2):
        for node in walk_own(fn_node):
            value = None
            if isinstance(node, ast.Return) and node.value is not None:
                value = node.value
            elif isinstance(node, ast.Assign):
                targets = _flat_targets(node.targets)
                if any(isinstance(t, (ast.Attribute, ast.Subscript))
                       for t in targets) \
                        or any(isinstance(t, ast.Name)
                               and t.id in escaped for t in targets):
                    value = node.value
            if value is not None:
                for sub in ast.walk(value):
                    if isinstance(sub, ast.Name) \
                            and isinstance(sub.ctx, ast.Load):
                        escaped.add(sub.id)
    return escaped


def _escapes(fn_node, call, escaped):
    """True when ``call``'s result leaves the function: it sits in a
    return/attribute/subscript store directly, or is bound to an
    escaped local name."""
    for node in walk_own(fn_node):
        if isinstance(node, ast.Return) and node.value is not None \
                and any(sub is call for sub in ast.walk(node.value)):
            return True
        if isinstance(node, ast.Assign) \
                and any(sub is call for sub in ast.walk(node.value)):
            targets = _flat_targets(node.targets)
            if any(isinstance(t, (ast.Attribute, ast.Subscript))
                   for t in targets):
                return True
            if any(isinstance(t, ast.Name) and t.id in escaped
                   for t in targets):
                return True
    return False


def _covered_by_finally(fn_node, open_call, close_method, leaf,
                        receivers):
    """True when some ``try`` in the function closes the resource in
    its ``finally`` and its extent covers the open call — the repo's
    idiom places ``begin`` either inside the try or on the line(s)
    immediately before it, so the predicate is by line range:
    open strictly before the finally suite, try block not ended
    before the open."""
    for node in walk_own(fn_node):
        if not isinstance(node, ast.Try) or not node.finalbody:
            continue
        if not (open_call.lineno < node.finalbody[0].lineno
                and node.end_lineno >= open_call.lineno):
            continue
        for stmt in node.finalbody:
            for close, close_leaf in _method_calls(
                    stmt, close_method, receivers):
                if receivers is None or close_leaf == leaf:
                    return True
    return False


def _in_with_item(fn_node, open_call):
    """True when the open call IS a ``with`` item's context expression
    (the context-manager form pairs by construction)."""
    for node in walk_own(fn_node):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if any(sub is open_call
                       for sub in ast.walk(item.context_expr)):
                    return True
    return False


class GatePairingAnalyzer(Analyzer):
    rule = "RIP014"
    name = "gate-pairing"
    description = ("chunk_gate begin/end, StagingPool acquire/release "
                   "and integrity begin_fold/finish_fold pair on every "
                   "path: try/finally, with, or (acquire only) "
                   "ownership escape")

    def run(self, ctx):
        if not ctx.relpath.startswith("riptide_tpu/"):
            return []
        findings = []
        for qual, fn in walk_functions(ctx.tree):
            escaped = None
            for open_m, close_m, receivers, may_escape in PAIRS:
                for call, leaf in _method_calls(fn, open_m, receivers):
                    if _covered_by_finally(fn, call, close_m, leaf,
                                           receivers):
                        continue
                    if _in_with_item(fn, call):
                        continue
                    if may_escape:
                        if escaped is None:
                            escaped = _escaped_names(fn)
                        if _escapes(fn, call, escaped):
                            continue
                    findings.append(Finding.at(
                        ctx, call, self.rule,
                        f"{leaf}.{open_m}(...) in {qual!r} has no "
                        f"matching {leaf}.{close_m}() in a covering "
                        "finally (and the result does not leave the "
                        "function) — a path that raises between them "
                        "leaks the resource; wrap in try/finally"))
        findings.sort(key=lambda f: (f.path, f.line, f.col))
        return findings
