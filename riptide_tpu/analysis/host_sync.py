"""
RIP001 — host-sync detector.

A single stray host synchronisation in the wrong place silently
serialises the whole search: inside a jit-traced body it either breaks
tracing or constant-folds a device transfer into the program; inside
the engine/batcher *queueing* hot path it stalls the dispatch pipeline
the queue-ahead design exists to keep full (PAPER.md's throughput
posture; the wire/device overlap of search/engine.py).

Two scopes, both precise by construction so the baseline stays small:

* **jit bodies** — functions decorated with ``jax.jit`` /
  ``partial(jax.jit, ...)`` / ``cached_jit(...)``: flags ``.item()``,
  ``.tolist()``, ``.block_until_ready()``, ``jax.device_get``, numpy
  pulls (``np.asarray`` / ``np.array`` / ``np.ascontiguousarray``) and
  ``float()`` / ``int()`` on non-literal arguments (host round trips at
  trace time);
* **queueing hot paths** — the explicitly-listed enqueue-side functions
  of the engine and batcher (collect/sync points are deliberately NOT
  listed — syncing is their job): flags ``.item()``, ``.tolist()``,
  ``.block_until_ready()``, ``jax.device_get`` and the numpy pulls.

Intentional sync points (e.g. the one documented device pull of
``run_periodogram``) live in the baseline with a justification.
"""
import ast

from .core import Analyzer, Finding, call_name, dotted, walk_functions

__all__ = ["HostSyncAnalyzer", "HOT_FUNCTIONS"]

# Queue-side hot functions per module: these run between batches while
# the device pipeline must stay fed, so a device->host pull here is a
# throughput bug even when it is semantically harmless.
HOT_FUNCTIONS = {
    "riptide_tpu/search/engine.py": {
        "_queue_stages", "queue_search_batch", "ship_stage_data",
        "_run_stage_fused", "_run_stage_kernel", "_run_stage_gather",
        "run_periodogram", "run_periodogram_batch",
    },
    "riptide_tpu/pipeline/batcher.py": {
        "BatchSearcher.process_stream", "BatchSearcher._queue_chunk",
        "BatchSearcher._queue_range", "BatchSearcher._ship_chunk",
    },
    "riptide_tpu/ops/ffa_kernel.py": {
        "CycleKernel.run_fused", "CycleKernel.__call__",
    },
}

_SYNC_ATTRS = {"item", "tolist", "block_until_ready"}
_NP_PULLS = {"asarray", "array", "ascontiguousarray"}
_NP_NAMES = {"np", "numpy", "onp"}


def _is_jit_decorated(fn):
    """True for @jax.jit / @jit / @partial(jax.jit, ...) /
    @functools.partial(jax.jit, ...) / @cached_jit(...)."""
    for deco in fn.decorator_list:
        name = dotted(deco) or ""
        if name.split(".")[-1] in ("jit", "cached_jit"):
            return True
        if isinstance(deco, ast.Call):
            cname = dotted(deco.func) or ""
            if cname.split(".")[-1] in ("jit", "cached_jit"):
                return True
            if cname.split(".")[-1] == "partial" and deco.args:
                inner = dotted(deco.args[0]) or ""
                if inner.split(".")[-1] in ("jit", "cached_jit"):
                    return True
    return False


def _np_pull(node):
    """True for np.asarray/np.array/np.ascontiguousarray calls."""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    return (isinstance(f, ast.Attribute) and f.attr in _NP_PULLS
            and isinstance(f.value, ast.Name) and f.value.id in _NP_NAMES)


def _literal(node):
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.UnaryOp) and isinstance(node.operand,
                                                    ast.Constant):
        return True
    return False


class HostSyncAnalyzer(Analyzer):
    rule = "RIP001"
    name = "host-sync"
    description = ("no host synchronisation inside jit-traced bodies or "
                   "the engine/batcher queueing hot paths")

    def __init__(self, hot_functions=None):
        self.hot_functions = (HOT_FUNCTIONS if hot_functions is None
                              else hot_functions)
        self._seen_functions = {}

    def _scan(self, ctx, fn, where, in_jit):
        out = []
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in _SYNC_ATTRS \
                    and not node.args:
                out.append(Finding.at(
                    ctx, node, self.rule,
                    f"`.{f.attr}()` forces a device sync inside {where} — "
                    "keep the result on device or move the pull to the "
                    "collect side",
                ))
            elif (dotted(f) or "").endswith("device_get"):
                out.append(Finding.at(
                    ctx, node, self.rule,
                    f"`jax.device_get` inside {where} — device->host pull "
                    "on the enqueue path",
                ))
            elif _np_pull(node):
                out.append(Finding.at(
                    ctx, node, self.rule,
                    f"`{dotted(f)}` inside {where} materialises its "
                    "argument on the host (a silent device sync when fed "
                    "a device array)",
                ))
            elif in_jit and isinstance(f, ast.Name) \
                    and f.id in ("float", "int") and len(node.args) == 1 \
                    and not _literal(node.args[0]):
                out.append(Finding.at(
                    ctx, node, self.rule,
                    f"`{f.id}(...)` on a traced value inside {where} "
                    "breaks tracing (or constant-folds a host round "
                    "trip) — use jnp casts or static arguments",
                ))
        return out

    def begin(self, repo):
        self._seen_functions = {}

    def run(self, ctx):
        findings = []
        hot = self.hot_functions.get(ctx.relpath, set())
        seen = self._seen_functions.setdefault(ctx.relpath, set())
        for qual, fn in walk_functions(ctx.tree):
            seen.add(qual)
            if _is_jit_decorated(fn):
                findings.extend(self._scan(
                    ctx, fn, f"jit body `{qual}`", in_jit=True))
            elif qual in hot:
                findings.extend(self._scan(
                    ctx, fn, f"queueing hot path `{qual}`", in_jit=False))
        return findings

    def finalize(self, repo, contexts):
        """Staleness guard on the scope config: a renamed module or hot
        function must fail the lint loudly, not silently unscope it."""
        findings = []
        for rel, names in sorted(self.hot_functions.items()):
            seen = self._seen_functions.get(rel)
            if seen is None:
                findings.append(Finding(
                    rel, 1, 0, self.rule,
                    "hot-path module missing from the package — the "
                    "host-sync scope list (analysis/host_sync.py "
                    "HOT_FUNCTIONS) is stale; update it",
                ))
                continue
            for name in sorted(set(names) - seen):
                findings.append(Finding(
                    rel, 1, 0, self.rule,
                    f"hot-path function {name!r} no longer exists in "
                    "this module — the host-sync scope list "
                    "(analysis/host_sync.py HOT_FUNCTIONS) is stale; "
                    "update it or the queueing path goes unchecked",
                ))
        return findings
