"""
jaxpr-level program contracts: the SEMANTIC static pass (rprove).

riplint's AST analyzers (the rest of this package) enforce source-level
discipline; the properties that actually decide survey throughput live
in the *traced computation*: how many XLA programs a plan dispatches,
how much HBM a DM-batch peaks at, whether a dtype silently widens to
float64, whether a declared donation is actually honoured. This module
extracts those properties WITHOUT any device execution — it abstractly
traces (``jax.make_jaxpr`` / AOT lowering, backend-free under
``JAX_PLATFORMS=cpu``) the exact programs the engine queues, via the
queued-stage lowering hooks in :mod:`riptide_tpu.search.engine`
(``staged_stage_programs`` / ``staged_chunk_program``) — and condenses
them into one **program contract** per representative search plan:

* **dispatch counts by kind** per stage (fused/pack/kernel/unpack/
  gather/slice — the fused path must queue one fused program per
  eligible stage lane bucket and ZERO pack programs);
* a **peak-HBM-bytes model** ``const + per_dm * D`` from a buffer-
  liveness walk over the whole-chunk jaxpr at two DM-batch probes
  (consumed by the batcher's model-seeded DM-batch pick, so OOM
  bisection becomes a fallback instead of the first resort);
* a **dtype-flow audit** (no float64/complex128 anywhere in the traced
  programs; the assembled S/N cube stays float32 — the accumulator
  dtype the S/N error budget requires);
* **host<->device transfer** count/bytes per stage (exact from the
  wire layout);
* **donation verification** (a program that declares donated inputs
  must actually alias them to outputs — a dropped donation silently
  doubles that buffer's footprint).

Contracts are pinned in ``tools/plan_contracts.json`` (the
``kernel_digest.json`` workflow: ``tools/rprove.py --update`` re-pins,
any drift is exit 1 in ``make prove`` / ``make check-full``).

Unlike its sibling analyzers this module NEEDS jax, so it is
deliberately **not** imported by ``riptide_tpu/analysis/__init__.py``
— the riplint runner's standalone load of the analysis package stays
jax-free. Import it explicitly (``riptide_tpu.analysis.jaxpr_contract``
or by file path from ``tools/rprove.py``).
"""
import json

import jax
import numpy as np

__all__ = [
    "PROBE_D", "HBM_PROBES", "RULES", "HBMModel", "aval_bytes",
    "peak_live_bytes", "count_f64_eqns", "collect_dtypes",
    "donation_report", "hbm_model", "build_contract_plan",
    "extract_contract", "check_contracts", "load_contracts",
]

# DM-batch size the per-stage programs are traced at (out_bytes divide
# exactly), and the two probes the linear peak-HBM model is fit from.
PROBE_D = 2
HBM_PROBES = (1, 3)

# Dispatch-kind metrics the engine's _count_dispatch maintains.
_DISPATCH_KINDS = ("fused", "pack", "kernel", "unpack", "gather",
                   "slice")

# Rule ids of the semantic pass (rprove's SARIF metadata; stable API
# like the RIPxxx ids).
RULES = (
    ("RPV001", "dispatch-drift",
     "per-stage device-program dispatch counts match the pinned "
     "contract; fused stages queue zero pack programs"),
    ("RPV002", "dtype-flow",
     "no float64/complex128 anywhere in the traced programs and the "
     "assembled S/N output dtype is pinned"),
    ("RPV003", "donation",
     "declared donated inputs are actually aliased to outputs"),
    ("RPV004", "transfer-drift",
     "host<->device transfer counts/bytes and closed-over operand "
     "bytes match the pinned contract"),
    ("RPV005", "hbm-model-drift",
     "the buffer-liveness peak-HBM model (const + per_dm * D) matches "
     "the pinned contract"),
    ("RPV006", "contract-set",
     "every contract plan is pinned and every pinned plan still "
     "exists"),
)

_F64 = ("float64", "complex128")


# ------------------------------------------------------------ jaxpr walks

def _is_var(v):
    """True for jaxpr Vars (Literals carry ``.val``)."""
    return not hasattr(v, "val")


def aval_bytes(aval):
    """Buffer bytes of one abstract value (0 for non-array avals such
    as tokens)."""
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    return int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize


def _sub_closed(eqn):
    """(jaxpr, consts) of every sub-jaxpr a call-like equation carries
    (pjit/closed_call/cond branches/...): the recursion points of the
    walks below."""
    out = []
    for val in eqn.params.values():
        items = val if isinstance(val, (tuple, list)) else (val,)
        for item in items:
            if hasattr(item, "jaxpr") and hasattr(item, "consts"):
                out.append((item.jaxpr, tuple(item.consts)))
            elif hasattr(item, "eqns") and hasattr(item, "invars"):
                out.append((item, ()))
    return out


def _walk_eqns(jaxpr):
    for eqn in jaxpr.eqns:
        yield eqn
        for sub, _ in _sub_closed(eqn):
            yield from _walk_eqns(sub)


def peak_live_bytes(closed):
    """Peak simultaneously-live buffer bytes of a (closed) jaxpr, from
    a liveness walk in equation order: a var is live from its defining
    equation (inputs/consts from entry) to its last use; outputs stay
    live to the end. Call-like equations contribute their own recursive
    peak beyond their operand/result bytes. This is a MODEL of the
    XLA-scheduled footprint — same operation order, no rematerialisation
    — pinned for drift detection and consumed (with a budget margin) by
    the batcher's seeded DM-batch pick."""
    jaxpr = getattr(closed, "jaxpr", closed)
    consts = tuple(getattr(closed, "consts", ()))
    const_bytes = sum(int(getattr(c, "nbytes", 0)) for c in consts)
    return _peak_live(jaxpr, const_bytes)


def _peak_live(jaxpr, const_bytes):
    last_use = {}
    for idx, eqn in enumerate(jaxpr.eqns):
        for v in eqn.invars:
            if _is_var(v):
                last_use[id(v)] = idx
    out_ids = {id(v) for v in jaxpr.outvars if _is_var(v)}
    live = {}
    for v in list(jaxpr.invars) + list(jaxpr.constvars):
        if _is_var(v):
            live[id(v)] = aval_bytes(v.aval)
    peak = sum(live.values()) + const_bytes
    for idx, eqn in enumerate(jaxpr.eqns):
        inner_extra = 0
        for sub, consts in _sub_closed(eqn):
            cb = sum(int(getattr(c, "nbytes", 0)) for c in consts)
            io = sum(aval_bytes(v.aval) for v in eqn.invars
                     if _is_var(v))
            io += sum(aval_bytes(v.aval) for v in eqn.outvars)
            inner_extra = max(inner_extra,
                              _peak_live(sub, cb) - io)
        for v in eqn.outvars:
            live[id(v)] = aval_bytes(v.aval)
        peak = max(peak, sum(live.values()) + const_bytes
                   + max(0, inner_extra))
        for v in eqn.invars:
            if _is_var(v) and last_use.get(id(v)) == idx \
                    and id(v) not in out_ids:
                live.pop(id(v), None)
        for v in eqn.outvars:
            if id(v) not in last_use and id(v) not in out_ids:
                live.pop(id(v), None)
    return peak


def count_f64_eqns(closed):
    """How many equations (recursively) produce a float64/complex128
    output — the dtype-flow audit's hard zero."""
    jaxpr = getattr(closed, "jaxpr", closed)
    n = 0
    for eqn in _walk_eqns(jaxpr):
        if any(str(getattr(v.aval, "dtype", "")) in _F64
               for v in eqn.outvars):
            n += 1
    return n


def collect_dtypes(closed):
    """Sorted dtype names of every var in the (recursive) jaxpr."""
    jaxpr = getattr(closed, "jaxpr", closed)
    seen = set()

    def scan(jx):
        for v in list(jx.invars) + list(jx.constvars) + list(jx.outvars):
            d = getattr(getattr(v, "aval", None), "dtype", None)
            if d is not None:
                seen.add(str(d))
        for eqn in jx.eqns:
            for v in eqn.outvars:
                d = getattr(getattr(v, "aval", None), "dtype", None)
                if d is not None:
                    seen.add(str(d))
            for sub, _ in _sub_closed(eqn):
                scan(sub)

    scan(jaxpr)
    return sorted(seen)


# -------------------------------------------------------------- donation

def donation_report(fn, args, donate_argnums=()):
    """``{"donated": n, "dropped": m}`` for one program via AOT
    lowering (no execution): a donated input XLA can actually reuse
    carries a ``tf.aliasing_output`` attribute in the lowered module;
    a declared donation with no alias was DROPPED (shape/dtype
    mismatch, or the buffer outlives the call) and silently doubles
    that buffer's footprint."""
    donate = tuple(donate_argnums)
    if not donate:
        return {"donated": 0, "dropped": 0}
    import warnings

    with warnings.catch_warnings():
        # jax warns about unusable donations; the report IS the signal.
        warnings.simplefilter("ignore")
        txt = jax.jit(fn, donate_argnums=donate).lower(*args).as_text()
    honored = txt.count("tf.aliasing_output")
    return {"donated": len(donate),
            "dropped": max(0, len(donate) - honored)}


# ------------------------------------------------------------- HBM model

class HBMModel:
    """Linear peak-HBM model ``bytes(D) = const + per_dm * D`` fit from
    the whole-chunk liveness walk at two DM-batch probes."""

    def __init__(self, const_bytes, per_dm_bytes):
        self.const_bytes = int(const_bytes)
        self.per_dm_bytes = int(per_dm_bytes)

    def predict(self, D):
        """Modelled peak bytes of a D-trial chunk."""
        return self.const_bytes + self.per_dm_bytes * int(D)

    def max_batch(self, budget_bytes):
        """Largest DM-batch the model predicts fits ``budget_bytes``
        (never below 1: a single trial must always be attempted — the
        OOM bisection floor owns the truly-impossible case). A
        D-independent footprint (``per_dm_bytes`` 0) fits at any batch
        size or at none; a cap is meaningless either way, so the model
        reports unbounded rather than forcing maximal splitting."""
        if self.per_dm_bytes <= 0:
            return 1 << 62
        return max(1, (int(budget_bytes) - self.const_bytes)
                   // self.per_dm_bytes)

    def to_dict(self):
        return {"const_bytes": self.const_bytes,
                "per_dm_bytes": self.per_dm_bytes}


def _warm_staged(plan, path, mode):
    """One throwaway whole-chunk trace per (plan, path, mode): the
    FIRST trace's side effects (device_put of the plan's memoized stage
    operands, kernel table uploads) change what later traces close
    over, so extraction always runs against the steady state a running
    survey sees — contracts stay deterministic across fresh and warm
    processes."""
    warmed = getattr(plan, "_contract_warmed", None)
    if warmed is None:
        warmed = plan._contract_warmed = set()
    if (path, mode) in warmed:
        return
    from ..search import engine

    fn, args = engine.staged_chunk_program(plan, 1, path=path, mode=mode)
    jax.make_jaxpr(fn)(*args)
    warmed.add((path, mode))


def _fit_hbm_model(plan, path, mode):
    from ..search import engine

    _warm_staged(plan, path, mode)
    peaks = []
    for D in HBM_PROBES:
        fn, args = engine.staged_chunk_program(plan, D, path=path,
                                               mode=mode)
        peaks.append(peak_live_bytes(jax.make_jaxpr(fn)(*args)))
    d0, d1 = HBM_PROBES
    per_dm = max(0, (peaks[1] - peaks[0]) // (d1 - d0))
    const = max(0, peaks[0] - per_dm * d0)
    return HBMModel(const, per_dm)


def hbm_model(plan, path=None, mode=None):
    """The plan's peak-HBM model, traced once per (path, mode) and
    cached on the plan (plans are lru-cached, so a survey pays one
    trace per distinct search configuration)."""
    from ..search import engine

    path = path or engine._ffa_path()
    mode = mode or engine._wire_mode(path)
    cache = getattr(plan, "_hbm_models", None)
    if cache is None:
        cache = plan._hbm_models = {}
    model = cache.get((path, mode))
    if model is None:
        model = cache[(path, mode)] = _fit_hbm_model(plan, path, mode)
    return model


# ------------------------------------------------------- contract extract

def build_contract_plan(spec):
    """The (cached) PeriodogramPlan of one ``CONTRACT_PLANS`` spec."""
    from ..search.plan import periodogram_plan

    return periodogram_plan(
        spec["size"], spec["tsamp"], tuple(spec["widths"]),
        spec["period_min"], spec["period_max"], spec["bins_min"],
        spec["bins_max"],
    )


def _dispatch_delta(trace):
    """Run ``trace`` (a make_jaxpr closure: executes the stage fn's
    host side, queueing nothing) and return (result, nonzero
    ``dispatch_<kind>`` counter deltas it fired)."""
    from ..survey.metrics import get_metrics

    m = get_metrics()
    before = {k: m.counter(f"dispatch_{k}") for k in _DISPATCH_KINDS}
    out = trace()
    delta = {k: int(m.counter(f"dispatch_{k}") - before[k])
             for k in _DISPATCH_KINDS}
    return out, {k: v for k, v in delta.items() if v}


def extract_contract(name, plan, path=None, mode=None, programs=None):
    """Extract one plan's full program contract (see module doc for the
    fields). ``programs`` overrides the engine's queued-stage records
    (:func:`riptide_tpu.search.engine.staged_stage_programs`) — the
    seeded-regression tests inject doctored program sets through it."""
    from ..search import engine

    path = path or engine._ffa_path()
    mode = mode or engine._wire_mode(path)
    _warm_staged(plan, path, mode)
    records = programs
    if records is None:
        records = engine.staged_stage_programs(plan, PROBE_D, path=path,
                                               mode=mode)

    wire = engine.wire_transfer_contract(plan, mode)
    per_wire = wire.pop("per_stage_wire_bytes_per_dm")
    stages = []
    dispatch_total = {}
    donated = dropped = 0
    dtypes = set()
    for r in records:
        closed, dispatch = _dispatch_delta(
            lambda r=r: jax.make_jaxpr(r["fn"])(*r["args"]))
        out_bytes = sum(aval_bytes(v.aval)
                        for v in closed.jaxpr.outvars)
        operand_bytes = sum(int(getattr(c, "nbytes", 0))
                            for c in closed.consts)
        i = r["stage"]
        rep = donation_report(r["fn"], r["args"], r.get("donate", ()))
        stages.append({
            "stage": i,
            "kind": r["kind"],
            "dispatch": dispatch,
            "operand_bytes": int(operand_bytes),
            "out_bytes_per_dm": int(out_bytes // PROBE_D),
            "wire_bytes_per_dm": int(per_wire[i]) if i < len(per_wire)
            else 0,
            "f64_eqns": count_f64_eqns(closed),
            "donation": rep,
        })
        for k, v in dispatch.items():
            dispatch_total[k] = dispatch_total.get(k, 0) + v
        donated += rep["donated"]
        dropped += rep["dropped"]
        dtypes.update(collect_dtypes(closed))

    chunk_fn, chunk_args = engine.staged_chunk_program(plan, PROBE_D,
                                                       path=path,
                                                       mode=mode)
    chunk_closed = jax.make_jaxpr(chunk_fn)(*chunk_args)
    out_dtype = str(chunk_closed.jaxpr.outvars[0].aval.dtype)
    model = hbm_model(plan, path=path, mode=mode)

    # The post-search peak program (PR 19): under default env semantics
    # RIPTIDE_DEVICE_CLUSTER is on, so the fused peak program carries
    # the on-device clustering + harmonic-screen sections. The block
    # pins its structure — the dtype-flow audit (RPV002 is absolute
    # here too) and the pulled bytes per DM trial, i.e. the size of the
    # ONE result pull the path contracts to.
    peak_fn, peak_args, pp = engine.staged_peak_program(plan, PROBE_D)
    peak_closed = jax.make_jaxpr(peak_fn)(*peak_args)
    peaks = {
        "device_cluster": bool(pp.device_cluster),
        "f64_eqns": count_f64_eqns(peak_closed),
        "out_bytes_per_dm": int(sum(aval_bytes(v.aval)
                                    for v in peak_closed.jaxpr.outvars)
                                // PROBE_D),
        "out_dtype": str(peak_closed.jaxpr.outvars[0].aval.dtype),
    }
    dtypes.update(collect_dtypes(peak_closed))

    return {
        "path": path,
        "wire_mode": mode,
        "n_stages": len(plan.stages),
        "stages": stages,
        "dispatch_total": dict(sorted(dispatch_total.items())),
        "transfers": wire,
        "donation": {"donated": int(donated), "dropped": int(dropped)},
        "dtypes": sorted(dtypes),
        "out_dtype": out_dtype,
        "peaks": peaks,
        "hbm": model.to_dict(),
    }


# --------------------------------------------------------- contract check

def _finding(rel, rule, message):
    return {"path": rel, "line": 1, "col": 0, "rule": rule,
            "message": message}


def check_contracts(pinned_doc, current, all_names,
                    contract_rel="tools/plan_contracts.json"):
    """Compare freshly-extracted contracts against the pinned document.

    ``current`` maps plan name -> contract (the subset this run
    traced); ``all_names`` is the FULL contract plan-set name list
    (every tier), so stale pinned entries are detected even when only
    the fast tier was re-traced. Returns riplint-shaped finding dicts
    (path/line/col/rule/message) — empty means zero drift. Two checks
    are ABSOLUTE (fail even if pinned agrees, because pinning them
    would bless a defect): float64 in a traced program, and a dropped
    donation."""
    pinned_plans = (pinned_doc or {}).get("plans", {})
    findings = []

    for stale in sorted(set(pinned_plans) - set(all_names)):
        findings.append(_finding(
            contract_rel, "RPV006",
            f"plan {stale!r}: pinned contract has no matching entry in "
            "ops.plan.CONTRACT_PLANS — delete it (rprove --update) or "
            "restore the plan spec"))

    for name in sorted(current):
        cur = current[name]
        # Absolute rules first: these fail on the CURRENT tree alone.
        for st in cur["stages"]:
            if st["f64_eqns"]:
                findings.append(_finding(
                    contract_rel, "RPV002",
                    f"plan {name!r} stage {st['stage']}: "
                    f"{st['f64_eqns']} float64-producing op(s) in the "
                    "traced program — the dtype-flow audit forbids f64 "
                    "on device (fix the promotion; --update cannot "
                    "bless it)"))
            if st["kind"] == "fused" and st["dispatch"].get("pack"):
                findings.append(_finding(
                    contract_rel, "RPV001",
                    f"plan {name!r} stage {st['stage']}: fused stage "
                    f"queues {st['dispatch']['pack']} pack program(s) "
                    "— the fused path's contract is one fused program "
                    "per lane bucket and ZERO pack programs"))
        for st in cur["stages"]:
            if st["donation"]["dropped"]:
                findings.append(_finding(
                    contract_rel, "RPV003",
                    f"plan {name!r} stage {st['stage']}: "
                    f"{st['donation']['dropped']} donated buffer(s) "
                    "dropped (declared but not aliased to any output) "
                    "— the donated HBM is silently double-counted; fix "
                    "the program shape or drop the donation"))

        pk = cur.get("peaks")
        if pk and pk.get("f64_eqns"):
            findings.append(_finding(
                contract_rel, "RPV002",
                f"plan {name!r} peak program: {pk['f64_eqns']} "
                "float64-producing op(s) in the traced program — the "
                "dtype-flow audit forbids f64 on device (fix the "
                "promotion; --update cannot bless it)"))

        pin = pinned_plans.get(name)
        if pin is None:
            findings.append(_finding(
                contract_rel, "RPV006",
                f"plan {name!r}: no pinned contract — run "
                "`python tools/rprove.py --update` and commit the "
                "result"))
            continue

        # Per-stage drift, most specific message first.
        pin_stages = {s["stage"]: s for s in pin.get("stages", ())}
        for st in cur["stages"]:
            ps = pin_stages.get(st["stage"])
            if ps is None:
                findings.append(_finding(
                    contract_rel, "RPV001",
                    f"plan {name!r} stage {st['stage']}: not in the "
                    "pinned contract (stage set changed) — re-pin with "
                    "--update if intentional"))
                continue
            if st["kind"] != ps.get("kind") \
                    or st["dispatch"] != ps.get("dispatch"):
                findings.append(_finding(
                    contract_rel, "RPV001",
                    f"plan {name!r} stage {st['stage']}: dispatch "
                    f"drift — pinned {ps.get('kind')}:"
                    f"{ps.get('dispatch')} != traced {st['kind']}:"
                    f"{st['dispatch']} (a changed/extra device program "
                    "per chunk; re-pin with --update only if "
                    "intentional)"))
            if st["operand_bytes"] != ps.get("operand_bytes"):
                findings.append(_finding(
                    contract_rel, "RPV004",
                    f"plan {name!r} stage {st['stage']}: closed-over "
                    f"operand bytes drift {ps.get('operand_bytes')} -> "
                    f"{st['operand_bytes']} — an unplanned host->device "
                    "transfer rides along with this stage's program"))
            if st["wire_bytes_per_dm"] != ps.get("wire_bytes_per_dm"):
                findings.append(_finding(
                    contract_rel, "RPV004",
                    f"plan {name!r} stage {st['stage']}: wire bytes "
                    f"per DM drift {ps.get('wire_bytes_per_dm')} -> "
                    f"{st['wire_bytes_per_dm']}"))
            if st["out_bytes_per_dm"] != ps.get("out_bytes_per_dm"):
                findings.append(_finding(
                    contract_rel, "RPV004",
                    f"plan {name!r} stage {st['stage']}: output bytes "
                    f"per DM drift {ps.get('out_bytes_per_dm')} -> "
                    f"{st['out_bytes_per_dm']}"))
            if st["donation"] != ps.get("donation"):
                findings.append(_finding(
                    contract_rel, "RPV003",
                    f"plan {name!r} stage {st['stage']}: donation "
                    f"drift — pinned {ps.get('donation')} != traced "
                    f"{st['donation']}"))
        for missing in sorted(set(pin_stages)
                              - {s["stage"] for s in cur["stages"]}):
            findings.append(_finding(
                contract_rel, "RPV001",
                f"plan {name!r} stage {missing}: pinned but no longer "
                "traced (stage set changed) — re-pin with --update if "
                "intentional"))

        if cur["transfers"] != pin.get("transfers"):
            findings.append(_finding(
                contract_rel, "RPV004",
                f"plan {name!r}: transfer contract drift — pinned "
                f"{pin.get('transfers')} != traced "
                f"{cur['transfers']}"))
        if cur["out_dtype"] != pin.get("out_dtype"):
            findings.append(_finding(
                contract_rel, "RPV002",
                f"plan {name!r}: assembled S/N dtype drift "
                f"{pin.get('out_dtype')} -> {cur['out_dtype']} — the "
                "f32 accumulator contract of the S/N error budget"))
        if cur["donation"] != pin.get("donation"):
            findings.append(_finding(
                contract_rel, "RPV003",
                f"plan {name!r}: donation contract drift — pinned "
                f"{pin.get('donation')} != traced {cur['donation']}"))
        if cur.get("peaks") != pin.get("peaks"):
            findings.append(_finding(
                contract_rel, "RPV001",
                f"plan {name!r}: peak-program contract drift — pinned "
                f"{pin.get('peaks')} != traced {cur.get('peaks')} (the "
                "fused peak program's structure or pulled bytes "
                "changed; re-pin with --update only if intentional)"))
        if cur["hbm"] != pin.get("hbm"):
            findings.append(_finding(
                contract_rel, "RPV005",
                f"plan {name!r}: peak-HBM model drift — pinned "
                f"{pin.get('hbm')} != traced {cur['hbm']} (the "
                "batcher's seeded DM-batch pick consumes this model; "
                "re-pin with --update after a deliberate memory-"
                "footprint change)"))
    return findings


def load_contracts(path):
    """The pinned contract document, or None when absent."""
    try:
        with open(path) as fobj:
            return json.load(fobj)
    except OSError:
        return None
