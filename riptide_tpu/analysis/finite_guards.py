"""
RIP006 — finite-guard discipline (ported from
``tools/check_finite_guards.py``, which remains as a thin shim).

Every public data entry point routes through the data-quality layer
(``riptide_tpu.quality``): a single NaN reaching the compute path
silently poisons a whole periodogram, so the guard is structural —
each checked function must (directly, or through one local helper)
invoke something from the quality module. See the original tool's
docstring for the full rationale; the logic here is the same AST
check, now emitting framework findings.
"""
import ast
import os

from .core import Analyzer, Finding

__all__ = ["FiniteGuardAnalyzer", "ENTRY_POINTS", "check_module", "check"]

# relpath (as stored, OS-independent forward slashes) -> required
# guarded function/method names
ENTRY_POINTS = {
    "riptide_tpu/ops/snr.py": [
        "boxcar_snr", "snr_batched",
    ],
    "riptide_tpu/time_series.py": [
        "from_binary", "from_npy_file", "from_presto_inf", "from_sigproc",
        "from_numpy_array", "generate", "normalise",
    ],
}


def _quality_aliases(tree):
    """Names bound (anywhere in the module, including inside function
    bodies) by ``from ...quality import X [as Y]``."""
    aliases = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module \
                and node.module.split(".")[-1] == "quality":
            for a in node.names:
                aliases.add(a.asname or a.name)
    return aliases


def _called_names(fn_node):
    """Names invoked inside a function body: bare calls by name,
    attribute calls by attribute name (covers self.x / cls.x /
    quality.x)."""
    direct_quality = False
    names = set()
    for node in ast.walk(fn_node):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Name):
            names.add(f.id)
        elif isinstance(f, ast.Attribute):
            names.add(f.attr)
            if isinstance(f.value, ast.Name) and f.value.id == "quality":
                direct_quality = True
    return names, direct_quality


def _functions(tree):
    """{name: node} over every (async) function/method in the module.
    Later definitions win, matching runtime shadowing."""
    out = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out[node.name] = node
    return out


def check_tree(tree, path, required):
    """Structured violations for one parsed module: list of
    ``(lineno, message)`` (lineno 1 for a missing entry point)."""
    aliases = _quality_aliases(tree)
    functions = _functions(tree)

    def guarded_directly(name):
        node = functions.get(name)
        if node is None:
            return False
        called, direct = _called_names(node)
        return direct or bool(called & aliases)

    violations = []
    for name in required:
        node = functions.get(name)
        if node is None:
            violations.append((1, f"entry point {name!r} not found "
                                  "(update the finite-guard entry-point "
                                  "list)"))
            continue
        if guarded_directly(name):
            continue
        # One level of indirection: a local helper that is itself guarded.
        called, _ = _called_names(node)
        if any(guarded_directly(h) for h in called if h in functions):
            continue
        violations.append((
            node.lineno,
            f"{name!r} does not route through the data-quality layer "
            "(riptide_tpu.quality)",
        ))
    return violations


def check_module(path, required):
    """Back-compat string API (used by tools/check_finite_guards.py and
    its tier-1 tests): one violation string per line."""
    with open(path) as fobj:
        tree = ast.parse(fobj.read(), filename=path)
    out = []
    for lineno, msg in check_tree(tree, path, required):
        if "not found" in msg:
            out.append(f"{path}: {msg}")
        else:
            out.append(f"{path}:{lineno}: {msg}")
    return out


def check(repo):
    """All violations (strings) across the configured entry points."""
    violations = []
    for rel, required in ENTRY_POINTS.items():
        violations.extend(
            check_module(os.path.join(repo, *rel.split("/")), required)
        )
    return violations


class FiniteGuardAnalyzer(Analyzer):
    rule = "RIP006"
    name = "finite-guards"
    description = ("public data entry points route through the "
                   "data-quality layer (riptide_tpu.quality)")

    def __init__(self, entry_points=None):
        self.entry_points = (ENTRY_POINTS if entry_points is None
                             else entry_points)
        self._seen = set()

    def begin(self, repo):
        self._seen = set()

    def run(self, ctx):
        required = self.entry_points.get(ctx.relpath)
        if required is None:
            return []
        self._seen.add(ctx.relpath)
        return [
            Finding(ctx.relpath, lineno, 0, self.rule, msg)
            for lineno, msg in check_tree(ctx.tree, ctx.path, required)
        ]

    def finalize(self, repo, contexts):
        # A configured module that never appeared means the lint went
        # vacuous (file moved/renamed without updating the config).
        return [
            Finding(rel, 1, 0, self.rule,
                    "configured finite-guard module missing from the "
                    "package — update the entry-point list")
            for rel in self.entry_points if rel not in self._seen
        ]
