"""
RIP009 — interprocedural lock-order and lock-coverage analysis.

RIP004 polices what happens *lexically inside* one module's critical
sections; it cannot see the cross-module surface where the survey's
deadlocks would actually form — the scheduler holding one subsystem's
lock while a call two modules away acquires another's (the watchdog /
status-provider / incident-sink web all run on different threads of
the same process). This analyzer lifts lock discipline to the
:class:`~riptide_tpu.analysis.core.ProjectContext` call graph:

* **lock discovery** — module-level ``X = threading.Lock()`` /
  ``RLock()`` objects and ``self.x = threading.Lock()`` instance locks
  (identified per class: the analysis treats all instances of a class
  as one lock, the standard static approximation);
* **held-set propagation** — every ``with <lock>:`` body (and explicit
  ``.acquire()`` of a known lock) records which locks are held;
  resolved calls made under a held lock propagate the held set into
  the callee, transitively, so an acquisition N calls away still
  yields an ordering edge. ``Thread(target=...)``/``submit`` handoffs
  deliberately do NOT propagate held locks — the child thread starts
  lock-free;
* **RIP009a: acquisition-order cycles** — an edge A->B means "B was
  acquired while A was held" somewhere in the program; any cycle in
  that global digraph is a deadlock-capable ordering inversion and is
  reported at each participating acquisition site. Module-level locks
  are singletons, so a self-edge on one (re-acquiring it beneath
  itself) is reported too; instance locks skip self-edges (two
  *instances* of a class may legitimately nest);
* **RIP009b: lock-free writes to guarded attributes** — an instance
  attribute (or module global) written under the class's (module's)
  own lock in one method but assigned on a lock-free path in another
  is a data race in waiting. ``__init__`` (module top level) is
  exempt — construction happens before publication — and so is a
  method whose every resolved call site in the project holds the lock
  (the ``_foo_locked`` helper pattern).

Intentional exceptions (build-serialisation locks that exist to block,
Pallas DMA ``.wait()`` look-alikes) carry baseline entries, same as
every other rule.
"""
import ast

from .core import Analyzer, Finding, dotted, walk_functions, walk_own

__all__ = ["LockOrderAnalyzer"]

_LOCK_CTORS = {"Lock", "RLock"}


def _is_lock_ctor(value):
    if not isinstance(value, ast.Call):
        return False
    name = dotted(value.func) or ""
    return name.split(".")[-1] in _LOCK_CTORS


def _ctor_kind(value):
    return (dotted(value.func) or "").split(".")[-1]


class _LockModel:
    """Discovered locks of one project: stable string ids
    (``relpath::NAME`` for module-level locks, ``relpath::Class.attr``
    for instance locks) plus enough structure to resolve an
    acquisition expression to one of them."""

    def __init__(self, project):
        self.project = project
        self.module_locks = {}    # (relpath, name) -> lock id
        self.class_locks = {}     # (relpath, class, attr) -> lock id
        self.kinds = {}           # lock id -> "Lock" | "RLock"
        for ctx in project.contexts:
            for node in ctx.tree.body:
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name) \
                        and _is_lock_ctor(node.value):
                    name = node.targets[0].id
                    lock_id = f"{ctx.relpath}::{name}"
                    self.module_locks[(ctx.relpath, name)] = lock_id
                    self.kinds[lock_id] = _ctor_kind(node.value)
            for qual, fn in walk_functions(ctx.tree):
                if "." not in qual:
                    continue
                cls = qual.split(".")[0]
                for sub in ast.walk(fn):
                    if isinstance(sub, ast.Assign) \
                            and len(sub.targets) == 1 \
                            and isinstance(sub.targets[0], ast.Attribute) \
                            and isinstance(sub.targets[0].value, ast.Name) \
                            and sub.targets[0].value.id == "self" \
                            and _is_lock_ctor(sub.value):
                        attr = sub.targets[0].attr
                        lock_id = f"{ctx.relpath}::{cls}.{attr}"
                        self.class_locks[(ctx.relpath, cls, attr)] = \
                            lock_id
                        self.kinds[lock_id] = _ctor_kind(sub.value)

    def is_module_level(self, lock_id):
        return lock_id in self.module_locks.values()

    def is_reentrant(self, lock_id):
        return self.kinds.get(lock_id) == "RLock"

    def resolve(self, relpath, owner_class, expr):
        """Lock id acquired by a with-item context expression (or the
        receiver of an ``.acquire()``), or None."""
        name = dotted(expr)
        if name is None:
            return None
        parts = name.split(".")
        if len(parts) == 1:
            local = self.module_locks.get((relpath, parts[0]))
            if local:
                return local
            binding = self.project._imports.get(relpath, {}).get(parts[0])
            if binding and binding[0] == "symbol":
                return self.module_locks.get((binding[1], binding[2]))
            return None
        if parts[0] == "self" and owner_class is not None:
            if len(parts) == 2:
                return self.class_locks.get(
                    (relpath, owner_class, parts[1]))
            if len(parts) == 3:
                typ = self.project.attr_types.get(
                    (relpath, owner_class, parts[1]))
                if typ:
                    return self.class_locks.get(
                        (typ[0], typ[1], parts[2]))
            return None
        # mod._lock through an import binding, or instance._lock
        # through a typed module variable / local.
        binding = self.project._imports.get(relpath, {}).get(parts[0])
        if binding and binding[0] == "module" and len(parts) == 2:
            return self.module_locks.get((binding[1], parts[1]))
        typ = self.project.var_types.get((relpath, parts[0]))
        if typ and len(parts) == 2:
            return self.class_locks.get((typ[0], typ[1], parts[1]))
        return None


class LockOrderAnalyzer(Analyzer):
    rule = "RIP009"
    name = "lock-order"
    description = ("no acquisition-order cycles across the whole "
                   "program (held-lock sets propagated through the "
                   "call graph) and no lock-free writes to attributes "
                   "guarded elsewhere")
    needs_project = True

    def begin(self, repo):
        self._fn_nodes = {}

    def run_project(self, project):
        self._fn_nodes = {fqn: info.node
                          for fqn, info in project.functions.items()}
        model = _LockModel(project)
        # Per function: direct acquisitions, calls made per held set,
        # write sites, and the held set active at each resolved call.
        acquires = {}        # fqn -> {lock id}
        order_edges = {}     # (A, B) -> witness (ctx, node, fqn)
        calls_under = []     # (caller fqn, callee fqn, frozenset(held))
        held_at_call = {}    # (callee fqn) -> list of held frozensets
        writes = []          # (fqn, ctx, node, target key, guarded locks)

        for fqn, info in project.functions.items():
            ctx = project.context_of(fqn)
            owner = info.qual.split(".")[0] if "." in info.qual else None
            acquires[fqn] = set()

            def explicit_ops(stmt):
                """(lock, "acquire"|"release") effects of one
                statement, any depth (nested defs excluded), in SOURCE
                order — walk_own's own order is stack-driven, and a
                self-contained ``try: A.acquire() ... finally:
                A.release()`` must net to nothing, which only holds
                when the acquire is applied before the release. Feeds
                the sequential held-set tracking so manual acquire
                regions hold their lock for the statements between."""
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    # walk_own skips nested defs it ENCOUNTERS but
                    # walks a root it is GIVEN: a statement that is
                    # itself a def is wholly deferred code.
                    return []
                ops = []
                for sub in walk_own(stmt):
                    if isinstance(sub, ast.Call) \
                            and isinstance(sub.func, ast.Attribute) \
                            and sub.func.attr in ("acquire", "release"):
                        lock = model.resolve(ctx.relpath, owner,
                                             sub.func.value)
                        if lock is not None:
                            ops.append((sub.lineno, sub.col_offset,
                                        lock, sub.func.attr))
                return [(lock, op) for _, _, lock, op in sorted(ops)]

            def visit_block(stmts, held):
                cur = set(held)
                for stmt in stmts:
                    visit(stmt, frozenset(cur))
                    for lock, op in explicit_ops(stmt):
                        if op == "acquire":
                            cur.add(lock)
                        else:
                            cur.discard(lock)

            def visit(node, held):
                if node is not info.node and isinstance(
                        node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    # A nested def is a separate FunctionInfo whose
                    # code is deferred: its calls/acquisitions belong
                    # to IT, and merely defining it under a lock holds
                    # nothing.
                    return
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    held_now = set(held)
                    for item in node.items:
                        # Calls in the with-item position run before
                        # (or between) the acquisitions and must be
                        # seen under whatever is held so far.
                        visit(item.context_expr, frozenset(held_now))
                        lock = model.resolve(ctx.relpath, owner,
                                             item.context_expr)
                        if lock is not None:
                            acquires[fqn].add(lock)
                            for h in held_now:
                                order_edges.setdefault(
                                    (h, lock),
                                    (ctx, item.context_expr, fqn))
                            held_now.add(lock)
                    visit_block(node.body, frozenset(held_now))
                    return
                if isinstance(node, ast.Call):
                    f = node.func
                    if isinstance(f, ast.Attribute) and \
                            f.attr == "acquire":
                        lock = model.resolve(ctx.relpath, owner, f.value)
                        if lock is not None:
                            acquires[fqn].add(lock)
                            for h in held:
                                order_edges.setdefault(
                                    (h, lock), (ctx, node, fqn))
                    callee = project.callee(node)
                    if callee is not None:
                        frozen = frozenset(held)
                        calls_under.append((fqn, callee, frozen))
                        held_at_call.setdefault(callee, []).append(frozen)
                self._record_writes(ctx, fqn, owner, node, held, model,
                                    writes)
                # Statement lists recurse through visit_block so a
                # manual acquire's effect reaches its later siblings.
                for _field, value in ast.iter_fields(node):
                    if isinstance(value, list):
                        if value and isinstance(value[0], ast.stmt):
                            visit_block(value, held)
                        else:
                            for v in value:
                                if isinstance(v, ast.AST):
                                    visit(v, held)
                    elif isinstance(value, ast.AST):
                        visit(value, held)

            visit(info.node, frozenset())

        # Transitive closure: every lock a function may acquire through
        # plain calls (thread handoffs start lock-free).
        closure = {fqn: set(locks) for fqn, locks in acquires.items()}
        changed = True
        while changed:
            changed = False
            for fqn, info in project.functions.items():
                mine = closure[fqn]
                before = len(mine)
                for _, callee, kind in info.calls:
                    if kind == "call" and callee in closure:
                        mine |= closure[callee]
                if len(mine) != before:
                    changed = True

        for caller, callee, held in calls_under:
            if not held:
                continue
            witness = None
            for h in held:
                for lock in closure.get(callee, ()):
                    key = (h, lock)
                    if key not in order_edges:
                        # Witness at the call site that carries the
                        # held lock into the acquiring callee.
                        if witness is None:
                            witness = self._call_witness(
                                project, caller, callee)
                        order_edges[key] = witness

        findings = self._cycle_findings(project, model, order_edges)
        findings.extend(self._write_findings(project, model, writes,
                                             held_at_call))
        return findings

    # -- RIP009a: ordering cycles -------------------------------------------

    def _call_witness(self, project, caller, callee):
        info = project.functions[caller]
        for node, c, kind in info.calls:
            if c == callee and kind == "call":
                return (project.context_of(caller), node, caller)
        return (project.context_of(caller), info.node, caller)

    def _cycle_findings(self, project, model, order_edges):
        graph = {}
        for (a, b), _ in order_edges.items():
            if a == b:
                continue
            graph.setdefault(a, set()).add(b)
        # Nodes sharing a strongly connected component participate in
        # at least one cycle; iterative Tarjan keeps deep graphs safe.
        index = {}
        low = {}
        stack, on_stack = [], set()
        sccs = {}
        counter = [0]

        def strongconnect(root):
            work = [(root, iter(sorted(graph.get(root, ()))))]
            index[root] = low[root] = counter[0]
            counter[0] += 1
            stack.append(root)
            on_stack.add(root)
            while work:
                node, it = work[-1]
                advanced = False
                for nxt in it:
                    if nxt not in index:
                        index[nxt] = low[nxt] = counter[0]
                        counter[0] += 1
                        stack.append(nxt)
                        on_stack.add(nxt)
                        work.append((nxt, iter(sorted(graph.get(nxt,
                                                                ())))))
                        advanced = True
                        break
                    if nxt in on_stack:
                        low[node] = min(low[node], index[nxt])
                if advanced:
                    continue
                work.pop()
                if low[node] == index[node]:
                    comp = set()
                    while True:
                        top = stack.pop()
                        on_stack.discard(top)
                        comp.add(top)
                        if top == node:
                            break
                    for member in comp:
                        sccs[member] = frozenset(comp)
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])

        for node in sorted(set(graph) | {b for bs in graph.values()
                                         for b in bs}):
            if node not in index:
                strongconnect(node)

        findings = []
        for (a, b), (ctx, node, fqn) in sorted(
                order_edges.items(), key=lambda kv: kv[0]):
            if a == b:
                # Module-level locks are singletons, so re-acquisition
                # beneath itself is a certain self-deadlock — unless
                # the lock is an RLock, whose whole point is reentrant
                # acquisition. Instance locks skip self-edges entirely
                # (two instances of a class may legitimately nest).
                if model.is_module_level(a) and not model.is_reentrant(a):
                    findings.append(Finding.at(
                        ctx, node, self.rule,
                        f"lock `{a}` is re-acquired on a path that "
                        f"already holds it (via `{fqn.split('::')[-1]}`)"
                        " — a non-reentrant Lock self-deadlocks here",
                    ))
                continue
            comp = sccs.get(a)
            if comp and b in comp and len(comp) > 1:
                cycle = " -> ".join(sorted(comp) + [sorted(comp)[0]])
                findings.append(Finding.at(
                    ctx, node, self.rule,
                    f"lock-order inversion: `{b}` is acquired while "
                    f"`{a}` is held (in `{fqn.split('::')[-1]}`), but "
                    f"the global acquisition graph also orders them the "
                    f"other way — cycle {cycle}; pick ONE order and "
                    "move the offending acquisition outside the "
                    "critical section",
                ))
        return findings

    # -- RIP009b: lock-free writes to guarded attributes --------------------

    def _record_writes(self, ctx, fqn, owner, node, held, model, writes):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        # `prev, _sink = _sink, sink` writes _sink just as surely.
        targets = [e for t in targets
                   for e in (t.elts if isinstance(t, (ast.Tuple, ast.List))
                             else (t,))]
        for t in targets:
            key = None
            if isinstance(t, ast.Attribute) and \
                    isinstance(t.value, ast.Name) and t.value.id == "self" \
                    and owner is not None:
                if (ctx.relpath, owner, t.attr) in model.class_locks:
                    continue  # the lock object itself
                key = ("attr", ctx.relpath, owner, t.attr)
            elif isinstance(t, ast.Name) and "." not in fqn.split("::")[1] \
                    and self._is_global_write(fqn, t.id):
                key = ("global", ctx.relpath, t.id)
            if key is not None:
                writes.append((fqn, ctx, node, key, frozenset(held)))

    def _is_global_write(self, fqn, name):
        # Only writes declared `global NAME` in the function count as
        # module-state writes; plain locals are invisible elsewhere.
        fn = self._fn_nodes.get(fqn)
        if fn is None:
            return False
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Global) and name in sub.names:
                return True
        return False

    def _write_findings(self, project, model, writes, held_at_call):
        # Relevant guard lock per write scope: the owning class's own
        # locks (module's own locks for globals).
        def own_locks(key):
            if key[0] == "attr":
                _, rel, cls, _ = key
                return {lock for (r, c, _a), lock
                        in model.class_locks.items()
                        if r == rel and c == cls}
            _, rel, _ = key
            return {lock for (r, _n), lock in model.module_locks.items()
                    if r == rel}

        by_key = {}
        for fqn, ctx, node, key, held in writes:
            by_key.setdefault(key[1:] + (key[0],), []).append(
                (fqn, ctx, node, key, held))

        findings = []
        for sites in by_key.values():
            locks = own_locks(sites[0][3])
            if not locks:
                continue
            guarded = [s for s in sites if s[4] & locks
                       and not s[0].endswith(("__init__",))]
            if not guarded:
                continue
            for fqn, ctx, node, key, held in sites:
                if held & locks:
                    continue
                qual = fqn.split("::")[1]
                if qual.endswith("__init__") or qual == "<module>":
                    continue
                # Caller mitigation: every resolved project call site
                # of this function holds one of the guarding locks
                # (the `_foo_locked` helper pattern).
                callers = held_at_call.get(fqn)
                if callers and all(h & locks for h in callers):
                    continue
                what = (f"self.{key[3]}" if key[0] == "attr"
                        else key[2])
                lock_names = ", ".join(sorted(locks))
                findings.append(Finding.at(
                    ctx, node, self.rule,
                    f"`{what}` is written under {lock_names} elsewhere "
                    f"but assigned lock-free in `{qual}` — either take "
                    "the lock here or document the field as "
                    "single-threaded",
                ))
        findings.sort(key=lambda f: (f.path, f.line, f.col))
        return findings
