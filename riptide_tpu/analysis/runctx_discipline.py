"""
RIP012 — runctx thread discipline over the whole-program call graph.

The run-context layer (``utils/runctx.py``) carries a job's incident
sink, status provider and storage-fault flags in a thread-local; PR 17
made every incident context-routed so a multi-tenant daemon never
mixes two jobs' journals. That property dies silently the moment a
thread is started whose target neither went through ``runctx.wrap``
(which captures the spawning thread's context and re-installs it in
the child) nor establishes its own context via ``install``/
``activate`` — every ``incidents.emit`` under that thread falls back
to the process-global sink. ripsched's ``runctx`` model demonstrates
the failure dynamically (mutation ``unwrapped_worker``); this rule
pins the code shape statically:

* **prong 1 (scheduler/serve scope)**: a ``Thread(target=...)`` /
  ``executor.submit(fn, ...)`` site inside — or reachable from — the
  serve/survey planes whose resolved target is neither wrapped nor a
  context-establishing function;
* **prong 2 (anywhere)**: same shape, when the unwrapped target can
  additionally reach ``incidents.emit`` over plain call edges — the
  exact route by which a record escapes its job's journal.

Resolution is conservative (the :class:`ProjectContext` contract):
an unresolvable target contributes no finding. Alias forms are
understood per function — ``h = runctx.wrap(fn)`` marks ``h``
compliant, ``h = self._stage`` makes ``submit(h, ...)`` a finding
exactly like ``submit(self._stage, ...)``.
"""
import ast

from .core import Analyzer, Finding, dotted, walk_own

__all__ = ["RunctxDisciplineAnalyzer", "SCOPE_PREFIXES", "WRAP_FQN",
           "ESTABLISH_FQNS", "EMIT_FQN"]

# The planes whose thread spawns must carry a job context (prong 1):
# everything the daemon multiplexes between tenants.
SCOPE_PREFIXES = ("riptide_tpu/serve/", "riptide_tpu/survey/")

WRAP_FQN = "riptide_tpu/utils/runctx.py::wrap"
# A target that (transitively) installs/activates its OWN context is
# compliant without wrap() — the daemon's per-job worker idiom.
ESTABLISH_FQNS = (
    "riptide_tpu/utils/runctx.py::install",
    "riptide_tpu/utils/runctx.py::activate",
)
EMIT_FQN = "riptide_tpu/survey/incidents.py::emit"


def _reverse_reachable(project, roots, kinds=("call",)):
    """Every fqn from which one of ``roots`` is reachable over edges of
    the given kinds (roots included when defined)."""
    rev = {}
    for info in project.functions.values():
        for _, callee, kind in info.calls:
            if kind in kinds:
                rev.setdefault(callee, set()).add(info.fqn)
    seen = {r for r in roots if r in project.functions}
    frontier = list(seen)
    while frontier:
        cur = frontier.pop()
        for caller in rev.get(cur, ()):
            if caller not in seen:
                seen.add(caller)
                frontier.append(caller)
    return seen


def _spawn_sites(fn_node):
    """``(call_node, target_expr)`` for every thread-of-execution
    handoff in a function's own body — the same leaf-name shapes the
    call-graph builder turns into "thread" edges."""
    for node in walk_own(fn_node):
        if not isinstance(node, ast.Call):
            continue
        leaf = (dotted(node.func) or "").split(".")[-1]
        if leaf == "Thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    yield node, kw.value
        elif leaf == "submit" and node.args:
            yield node, node.args[0]


class RunctxDisciplineAnalyzer(Analyzer):
    rule = "RIP012"
    name = "runctx-discipline"
    description = ("threads spawned from the serve/survey planes carry "
                   "a run context (runctx.wrap-ed target or a target "
                   "that installs its own), and no thread without a "
                   "context route can reach incidents.emit")
    needs_project = True

    def run_project(self, project):
        findings = []
        establish = _reverse_reachable(project, ESTABLISH_FQNS)
        emits = _reverse_reachable(project, (EMIT_FQN,))
        scope_roots = [fqn for fqn, info in project.functions.items()
                       if info.relpath.startswith(SCOPE_PREFIXES)]
        in_scope = set(project.reachable(scope_roots,
                                         kinds=("call", "thread")))

        for info in project.functions.values():
            owner = (info.qual.split(".")[0] if "." in info.qual
                     else None)
            # Per-function alias tables: handles bound by a SINGLE
            # plain assignment (`h = runctx.wrap(fn)` / `h = fn` /
            # `h = self._meth`) — the shapes the repo actually spawns.
            wrap_aliases = set()
            plain_aliases = {}
            for sub in walk_own(info.node):
                if not (isinstance(sub, ast.Assign)
                        and len(sub.targets) == 1
                        and isinstance(sub.targets[0], ast.Name)):
                    continue
                tgt = sub.targets[0].id
                if isinstance(sub.value, ast.Call) \
                        and project.callee(sub.value) == WRAP_FQN:
                    wrap_aliases.add(tgt)
                    plain_aliases.pop(tgt, None)
                    continue
                ref = project._resolve_callable_ref(
                    info.relpath, owner, {}, sub.value)
                if ref is not None:
                    plain_aliases[tgt] = ref
                    wrap_aliases.discard(tgt)

            for call, target in _spawn_sites(info.node):
                # Wrapped forms are compliant: a direct
                # runctx.wrap(...) argument, or a wrap-alias name.
                if isinstance(target, ast.Call) \
                        and project.callee(target) == WRAP_FQN:
                    continue
                if isinstance(target, ast.Name) \
                        and target.id in wrap_aliases:
                    continue
                if isinstance(target, ast.Name) \
                        and target.id in plain_aliases:
                    tgt_fqn = plain_aliases[target.id]
                else:
                    tgt_fqn = project._resolve_callable_ref(
                        info.relpath, owner, {}, target)
                if tgt_fqn is None or tgt_fqn in establish:
                    continue
                tgt_qual = project.functions[tgt_fqn].qual
                ctx = project.by_rel[info.relpath]
                if tgt_fqn in emits:
                    findings.append(Finding.at(
                        ctx, call, self.rule,
                        f"thread target {tgt_qual!r} is not "
                        "runctx.wrap-ed yet reaches incidents.emit "
                        "(via "
                        + " -> ".join(project.witness_path(
                            project.reachable([tgt_fqn]), EMIT_FQN))
                        + ") — its incidents land in the "
                        "process-global sink, not the job's journal"))
                elif info.fqn in in_scope:
                    findings.append(Finding.at(
                        ctx, call, self.rule,
                        f"thread target {tgt_qual!r} spawned from the "
                        "serve/survey plane without runctx.wrap (and "
                        "it does not install/activate its own "
                        "context) — wrap it or establish a context "
                        "inside it"))
        findings.sort(key=lambda f: (f.path, f.line, f.col))
        return findings
