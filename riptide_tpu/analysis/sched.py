"""
ripsched — deterministic schedule-exploration model checking of the
repo's concurrency protocols (PR 20).

riplint's static rules (RIP001-014) prove lexical and call-graph
properties; rprove proves jaxpr-level program contracts. Neither can
prove an *interleaving* property — that no schedule of the serve
daemon's job workers loses a wakeup, double-releases a staging buffer
or routes an incident into the wrong job's journal. This module closes
that gap with a small stateless model checker:

* the REAL protocol code is loaded with its synchronization primitives
  swapped for instrumented shims (:class:`SchedLock`,
  :class:`SchedCondition`, a virtual clock) driven by a cooperative
  :class:`Scheduler` — one task runs at a time, every blocking
  operation is a *decision point* where the scheduler picks who runs
  next;
* a bounded DFS (:func:`explore_model`) systematically enumerates
  interleavings under iterative preemption bounding (Musuvathi/Qadeer
  context bounding: all schedules with 0 preemptions, then exactly 1,
  ... up to ``--bound``), so the first violation found is minimal in
  preemptions;
* every run is replayable: the decision digits form a schedule ID
  (``model[+mutation]:digits``) that :func:`replay` re-executes
  byte-deterministically — the CI repro for any violation.

Four models cover the threaded surface PRs 16-19 grew. ``fairshare``
and ``staging`` and ``runctx`` execute the REAL repo code
(``serve/queue.py`` + ``serve/tenants.py`` loaded under a synthetic
package prefix so ``riptide_tpu/__init__`` — and jax — never imports;
``_StagingPool``/``release_prepared`` AST-extracted from
``search/engine.py``; ``utils/runctx.py`` loaded whole). The
``quarantine`` model mirrors the latch protocol of
``survey/integrity.py::IntegrityManager.quarantine`` plus the
scheduler's park-on-latch loop line-for-line (the real manager drags
journal/jax imports), and the runctx model's ``mini_emit`` copies
``survey/incidents.py::emit``'s context-first sink resolution — both
mirrors say so at their definition and must be updated with their
sources.

Timed waits are modeled as UNTIMED on purpose: production code's
``cond.wait(timeout=0.5)`` would eventually paper over a lost wakeup;
under the model a dropped ``notify_all`` parks its waiters forever and
surfaces as a detected deadlock instead of a 500 ms stutter.

Each invariant is proven non-vacuous by a named MUTATION that re-arms
a real bug shape (``drop_notify``, ``double_release``,
``unwrapped_worker``, ...); ``tools/ripsched.py --mutate`` and the
seeded-regression tests assert each one is detected with a printed
minimal schedule.

Importable with NO jax and NO ``riptide_tpu/__init__`` (the CLI loads
this file standalone by path, like riplint loads the analyzers).
Deliberately not imported by ``riptide_tpu/analysis/__init__`` — the
lint pass never pays for model loading — but living in ``analysis/``
keeps it inside riplint's analyzer digest, so the riplint cache
invalidates when the checker changes.
"""
import ast
import importlib
import importlib.util
import os
import random
import sys
import threading
import types

__all__ = [
    "InvariantViolation", "Scheduler", "SchedLock", "SchedCondition",
    "MODELS", "SARIF_RULES", "ExploreResult", "Violation",
    "explore_model", "replay", "parse_schedule_id", "format_schedule_id",
    "spec_doc", "env_default",
]

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# Decision budget per run: a schedule still undecided after this many
# scheduler choices is reported as a (non-)termination violation, never
# silently truncated.
DEFAULT_MAX_STEPS = 400
# Schedules explored per (model, mutation): hitting the cap is logged
# and marked on the result — bounded coverage must never read as
# exhaustive coverage.
DEFAULT_MAX_SCHEDULES = 800


class InvariantViolation(BaseException):
    """An invariant check failed mid-schedule. BaseException so the
    target code's own ``except Exception`` recovery paths (which are
    part of what is being model-checked) can never swallow it."""

    def __init__(self, invariant, message):
        super().__init__(f"[{invariant}] {message}")
        self.invariant = invariant
        self.message = message


class _TaskAbort(BaseException):
    """Unwinds a parked task when a run aborts (violation found or
    shutdown); BaseException so target-code ``except Exception``
    blocks cannot absorb the unwind."""


def _violate(invariant, message):
    raise InvariantViolation(invariant, message)


# -- the controlled scheduler -----------------------------------------------

class _Task:
    __slots__ = ("index", "name", "fn", "thread", "sem", "done", "pred",
                 "label", "exc")

    def __init__(self, index, name, fn):
        self.index = index
        self.name = name
        self.fn = fn
        self.thread = None
        self.sem = threading.Semaphore(0)
        self.done = False
        self.pred = None          # enabledness predicate (None = always)
        self.label = "start"      # what the task does when next granted
        self.exc = None


class Scheduler:
    """Cooperative sequentializer: model tasks run on real daemon
    threads but exactly one holds the (semaphore-passed) execution
    token at a time, yielding it back at every :meth:`op_point`. The
    controller picks the next task per the given ``schedule`` digits
    (replay / DFS prefix) and, past them, a deterministic default:
    keep the last task running while it is enabled, else the
    lowest-index enabled task — so the base schedule of any prefix
    uses zero additional preemptions.

    ``trace`` records ``(chosen_index, enabled_indices, label)`` per
    decision; the chosen indices ARE the schedule ID digits.
    """

    def __init__(self, schedule=(), max_steps=DEFAULT_MAX_STEPS):
        self.tasks = []
        self._by_thread = {}
        self._ctl = threading.Semaphore(0)
        self._schedule = tuple(int(d) for d in schedule)
        self.trace = []
        self.max_steps = int(max_steps)
        self.clock = 0.0
        self.violation = None     # (invariant id, message)
        self.diverged = None      # replay step whose digit was disabled
        self._abort = False
        self._lock_seq = 0        # per-run lock naming: replay renders
                                  # byte-identical traces

    # -- task-side API ---------------------------------------------------

    def spawn(self, name, fn):
        if len(self.tasks) >= 10:
            raise ValueError("schedule IDs encode one digit per task: "
                             "a model may declare at most 10 tasks")
        self.tasks.append(_Task(len(self.tasks), name, fn))

    def current_task(self):
        return self._by_thread.get(threading.get_ident())

    def current_name(self):
        task = self.current_task()
        return task.name if task is not None else "<main>"

    def op_point(self, pred=None, label="yield"):
        """One visible operation about to happen on the calling task:
        park, hand the token to the controller, resume when granted
        (the controller only grants a task whose ``pred`` holds, so
        the operation itself then runs atomically — no other task
        executes until the next op_point). On the controller/build
        thread this is a pass-through: the op runs immediately and a
        blocked one is a harness bug."""
        task = self.current_task()
        if task is None:
            if pred is not None and not pred():
                raise RuntimeError(
                    f"blocking operation {label!r} outside a scheduled "
                    "task (model build phase must not contend)")
            return
        if self._abort:
            raise _TaskAbort()
        task.pred = pred
        task.label = label
        self._ctl.release()
        task.sem.acquire()
        if self._abort:
            raise _TaskAbort()
        task.pred = None

    # -- controller ------------------------------------------------------

    def _task_main(self, task):
        self._by_thread[threading.get_ident()] = task
        task.sem.acquire()
        try:
            if not self._abort:
                task.fn()
        except (_TaskAbort, GeneratorExit):
            pass
        except InvariantViolation as vio:
            if self.violation is None:
                self.violation = (vio.invariant, vio.message)
        except BaseException as exc:  # a crashed task IS a finding
            task.exc = exc
            if self.violation is None:
                self.violation = (
                    "termination",
                    f"task {task.name!r} crashed: {exc!r}")
        finally:
            task.done = True
            self._ctl.release()

    def _choose(self, step, enabled, last):
        if step < len(self._schedule):
            want = self._schedule[step]
            for task in enabled:
                if task.index == want:
                    return task
            return None
        if last is not None and not last.done:
            for task in enabled:
                if task is last:
                    return task
        return enabled[0]

    def run(self):
        for task in self.tasks:
            task.thread = threading.Thread(
                target=self._task_main, args=(task,), daemon=True,
                name=f"ripsched-{task.name}")
            task.thread.start()
        step = 0
        last = None
        while self.violation is None:
            live = [t for t in self.tasks if not t.done]
            if not live:
                break
            enabled = [t for t in live
                       if t.pred is None or t.pred()]
            if not enabled:
                parked = ", ".join(
                    f"{t.name} ({t.label})" for t in live)
                self.violation = (
                    "no-lost-wakeup",
                    f"deadlock: no task is runnable; parked: {parked}")
                break
            if step >= self.max_steps:
                self.violation = (
                    "termination",
                    f"schedule exceeded the {self.max_steps}-decision "
                    "budget without quiescing")
                break
            chosen = self._choose(step, enabled, last)
            if chosen is None:
                self.diverged = step
                break
            self.trace.append((chosen.index,
                               tuple(t.index for t in enabled),
                               chosen.label))
            last = chosen
            self.clock += 1.0
            chosen.sem.release()
            self._ctl.acquire()
            step += 1
        self._shutdown()

    def _shutdown(self):
        self._abort = True
        for task in self.tasks:
            if not task.done:
                task.sem.release()
        for task in self.tasks:
            if task.thread is not None:
                task.thread.join(timeout=5.0)

    def digits(self):
        return "".join(str(c) for c, _, _ in self.trace)

    def trace_lines(self):
        lines = []
        for k, (chosen, enabled, label) in enumerate(self.trace):
            marks = "".join(str(i) for i in enabled)
            lines.append(f"  step {k:3d} [{marks}] -> "
                         f"{self.tasks[chosen].name}: {label}")
        return lines


# -- instrumented primitives -------------------------------------------------

class SchedLock:
    """``threading.Lock`` under scheduler control: ``acquire`` is a
    decision point enabled while the lock is free; ``release`` is NOT
    a decision point — a switch right after a release is only
    observable at the next acquire/wait, which is itself a decision
    point, so eliding it prunes equivalent schedules without losing
    any distinguishable interleaving."""

    def __init__(self, sched, name=None):
        self._sched = sched
        if name is None:
            sched._lock_seq += 1
            name = f"lock#{sched._lock_seq}"
        self.name = name
        self.owner = None

    def acquire(self, blocking=True, timeout=-1):
        self._sched.op_point(pred=lambda: self.owner is None,
                             label=f"acquire {self.name}")
        self.owner = self._sched.current_name()
        return True

    def release(self):
        self.owner = None

    def locked(self):
        return self.owner is not None

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class SchedRLock(SchedLock):
    """Reentrant variant (none of the current targets need one, but a
    target growing an RLock must not silently get non-reentrant
    semantics)."""

    def __init__(self, sched, name=None):
        super().__init__(sched, name)
        self._count = 0

    def acquire(self, blocking=True, timeout=-1):
        me = self._sched.current_name()
        self._sched.op_point(
            pred=lambda: self.owner is None or self.owner == me,
            label=f"acquire {self.name}")
        self.owner = me
        self._count += 1
        return True

    def release(self):
        self._count -= 1
        if self._count <= 0:
            self._count = 0
            self.owner = None


class SchedCondition:
    """``threading.Condition`` under scheduler control. ``wait`` is
    modeled UNTIMED even when the caller passes a timeout: production
    timeouts only bound how long a lost wakeup stalls the process, so
    honoring them would hide exactly the bug class this checker exists
    to find — a dropped notify parks its waiters forever and the
    scheduler reports the deadlock."""

    def __init__(self, lock=None, sched=None):
        self._sched = sched
        self._lock = lock if lock is not None else SchedLock(sched)
        self._waiting = []
        self._notified = set()

    def acquire(self, *a, **k):
        return self._lock.acquire(*a, **k)

    def release(self):
        self._lock.release()

    def __enter__(self):
        self._lock.acquire()
        return self

    def __exit__(self, *exc):
        self._lock.release()
        return False

    def wait(self, timeout=None):
        token = object()
        self._waiting.append(token)
        self._lock.release()
        self._sched.op_point(pred=lambda: token in self._notified,
                             label=f"wait on {self._lock.name}")
        self._waiting.remove(token)
        self._notified.discard(token)
        # Re-acquire races the other woken waiters: its own decision.
        self._lock.acquire()
        return True

    def wait_for(self, predicate, timeout=None):
        while not predicate():
            self.wait()
        return True

    def notify(self, n=1):
        for token in self._waiting[:n]:
            self._notified.add(token)

    def notify_all(self):
        self._notified.update(self._waiting)


class _ThreadingShim:
    """Drop-in for a target module's ``threading`` attribute: locks
    and conditions come under scheduler control, ``local`` stays the
    REAL thread-local class (model tasks are real threads, so real
    TLS — the thing the runctx model verifies — keeps its production
    semantics)."""

    def __init__(self, sched):
        self._sched = sched
        self.local = threading.local
        self.current_thread = threading.current_thread
        self.get_ident = threading.get_ident

    def Lock(self):
        return SchedLock(self._sched)

    def RLock(self):
        return SchedRLock(self._sched)

    def Condition(self, lock=None):
        return SchedCondition(lock, self._sched)


class _TimeShim:
    """Virtual clock: each read advances the scheduler's deterministic
    clock by one unit, so elapsed-time arithmetic in the target (turn
    charging) stays exact and replayable; ``sleep`` is a plain yield."""

    def __init__(self, sched):
        self._sched = sched

    def _tick(self):
        self._sched.clock += 1.0
        return self._sched.clock

    def perf_counter(self):
        return self._tick()

    def monotonic(self):
        return self._tick()

    def time(self):
        return self._tick()

    def sleep(self, seconds=0):
        self._sched.op_point(label=f"sleep({seconds})")


# -- loading the real protocol code (jax-free) -------------------------------

_TGT_PREFIX = "_ripsched_tgt"


def _ensure_target_pkg(repo):
    """Synthetic package skeleton over the real source tree: parent
    modules whose ``__path__`` points at the real directories, so
    ``import _ripsched_tgt.serve.queue`` loads the real file (and its
    ``from ..utils import envflags`` relative imports resolve) WITHOUT
    ever executing ``riptide_tpu/__init__`` — which imports jax."""
    if _TGT_PREFIX in sys.modules:
        return
    root = os.path.join(repo, "riptide_tpu")

    def pkg(name, path):
        mod = types.ModuleType(name)
        mod.__path__ = [path]
        mod.__package__ = name
        sys.modules[name] = mod

    pkg(_TGT_PREFIX, root)
    for sub in ("serve", "utils", "survey", "obs"):
        pkg(f"{_TGT_PREFIX}.{sub}", os.path.join(root, sub))


def load_target(repo, dotted_rel):
    """The real module ``riptide_tpu/<dotted_rel>`` under the synthetic
    prefix (cached across runs; re-instrumented per run)."""
    _ensure_target_pkg(repo)
    return importlib.import_module(f"{_TGT_PREFIX}.{dotted_rel}")


def instrument(mod, sched):
    """Point an already-loaded target module's ``threading`` / ``time``
    attributes at this run's shims. Primitive INSTANCES are created in
    the model build phase (after this call), so they bind the run's
    scheduler; module-level ``threading.local()`` objects from import
    time stay real, which is exactly right."""
    mod.threading = _ThreadingShim(sched)
    if hasattr(mod, "time"):
        mod.time = _TimeShim(sched)


def _load_staging_pool(repo, sched):
    """``_StagingPool`` + ``release_prepared`` AST-extracted from
    ``search/engine.py`` (the module itself imports jax at scope, so
    the two defs are compiled alone). ``_StagingPool.__init__`` does
    ``import threading`` INSIDE the method body — module-attribute
    patching cannot intercept that, so the exec globals carry an
    ``__import__`` that hands back the shim for ``threading``."""
    import builtins

    import numpy as np

    path = os.path.join(repo, "riptide_tpu", "search", "engine.py")
    with open(path) as fobj:
        tree = ast.parse(fobj.read(), filename=path)
    wanted = {"_StagingPool", "release_prepared"}
    picked = [node for node in tree.body
              if isinstance(node, (ast.ClassDef, ast.FunctionDef))
              and node.name in wanted]
    if {n.name for n in picked} != wanted:
        raise RuntimeError(
            f"search/engine.py no longer defines {sorted(wanted)} at "
            "module scope — update the staging model extraction")
    shim = _ThreadingShim(sched)
    real_import = builtins.__import__

    def _import(name, *args, **kwargs):
        if name == "threading":
            return shim
        return real_import(name, *args, **kwargs)

    bi = dict(vars(builtins))
    bi["__import__"] = _import
    glb = {"np": np, "__builtins__": bi, "__name__": "_ripsched_staging"}
    exec(compile(ast.Module(body=picked, type_ignores=[]), path, "exec"),
         glb)
    return glb["_StagingPool"], glb["release_prepared"]


# -- models ------------------------------------------------------------------

class ModelSpec:
    """One checkable model: its real-code targets, the invariants its
    runs assert, the named mutations that re-arm known-bad shapes, and
    the builder returning ``(tasks, final_check)``."""

    def __init__(self, name, description, targets, invariants,
                 mutations, build):
        self.name = name
        self.description = description
        self.targets = tuple(targets)
        self.invariants = tuple(invariants)   # (id, description) pairs
        self.mutations = dict(mutations)      # name -> description
        self.build = build


def _fair_key(queue, entry):
    return (entry.priority,
            queue._tenant_device_s.get(entry.tenant, 0.0),
            entry.device_s, entry.seq)


def _build_fairshare(repo, sched, mutation):
    qmod = load_target(repo, "serve.queue")
    tmod = load_target(repo, "serve.tenants")
    instrument(qmod, sched)
    instrument(tmod, sched)
    tenants = tmod.TenantTable(budget_device_s=0.0, max_active=8)
    queue = qmod.FairShareQueue(tenants)

    if mutation == "drop_notify":
        queue._cond.notify_all = lambda *a, **k: None
    elif mutation == "drop_charge":
        tenants.charge = lambda *a, **k: None
    elif mutation == "unfair_pick":
        def _fifo_pick():
            waiting = [e for e in queue._entries.values() if e.waiting]
            if not waiting:
                return None
            return min(waiting, key=lambda e: e.seq)
        queue._pick = _fifo_pick

    # Pick-minimality recorder: every grant decision the queue makes
    # must be the minimum of the documented fair-share key over the
    # waiting set — wraps whatever _pick is installed (including a
    # mutated one), so an unfair pick is caught at its first use.
    inner_pick = queue._pick

    def _checked_pick():
        entry = inner_pick()
        if entry is not None:
            waiting = [e for e in queue._entries.values() if e.waiting]
            best = min(waiting, key=lambda e: _fair_key(queue, e))
            if _fair_key(queue, entry) != _fair_key(queue, best):
                _violate(
                    "fair-share-pick",
                    f"_pick chose {entry.job_id!r} over {best.job_id!r} "
                    "— starves the tenant with the least charged device "
                    "time (fair key (priority, tenant_device_s, "
                    "device_s, seq))")
        return entry

    queue._pick = _checked_pick

    jobs = (("A1", "tenantA"), ("A2", "tenantA"), ("B1", "tenantB"))
    gates = {jid: queue.register(jid, tenant) for jid, tenant in jobs}
    state = {"turn": None, "completed": set(), "drained": set()}

    def _job(jid):
        def run():
            gate = gates[jid]
            try:
                for cid in range(2):
                    # The model DRIVES the raw protocol so the explorer
                    # can catch a missed end — the pairing rule is for
                    # production code.
                    gate.begin(cid)  # riplint: disable=RIP014
                    if state["turn"] is not None:
                        _violate(
                            "gate-mutual-exclusion",
                            f"{jid} granted chunk {cid} while "
                            f"{state['turn']} still holds the device "
                            "turn")
                    state["turn"] = jid
                    sched.op_point(label=f"device work chunk {cid}")
                    state["turn"] = None
                    gate.end(cid)
                state["completed"].add(jid)
            except qmod.JobDrained:
                if not queue._draining:
                    _violate("drain-termination",
                             f"{jid} drained while the queue was not "
                             "draining")
                state["drained"].add(jid)
            finally:
                queue.unregister(jid)
        return run

    def _drain():
        sched.op_point(label="issue drain")
        queue.drain()

    tasks = [(jid, _job(jid)) for jid, _ in jobs] + [("drain", _drain)]

    def final_check():
        out = []
        missing = {jid for jid, _ in jobs} \
            - state["completed"] - state["drained"]
        if missing:
            out.append((
                "drain-termination",
                f"job(s) {sorted(missing)} quiesced neither completed "
                "nor parked by drain — a non-terminal record survived"))
        charged = sum(queue._tenant_device_s.values())
        recorded = sum(tenants._spent.values())
        if abs(charged - recorded) > 1e-9:
            out.append((
                "charge-conservation",
                f"queue charged {charged:g} device-units but the "
                f"TenantTable recorded {recorded:g} — quota enforcement "
                "drifts from the fair-share accounting"))
        return out

    return tasks, final_check


def _build_staging(repo, sched, mutation):
    import numpy as np

    pool_cls, release_prepared = _load_staging_pool(repo, sched)
    pool = pool_cls(max_per_key=4)
    held = {}         # id(buf) -> (worker, chunk) currently in use
    journaled = set()

    def _free_ids():
        return [id(b) for stack in pool._free.values() for b in stack]

    def _release_checked(worker, cid, buf):
        if (worker, cid) not in journaled:
            _violate(
                "staging-release-after-journal",
                f"{worker} released chunk {cid}'s staging buffer before "
                "its journal record was appended (retry re-ship would "
                "read a recycled buffer)")
        held.pop(id(buf), None)
        release_prepared(pool, (buf, {"scales": None}))
        ids = _free_ids()
        if len(ids) != len(set(ids)):
            _violate(
                "staging-no-double-release",
                f"{worker} chunk {cid}: the pool free list holds the "
                "same buffer twice — the next two acquires alias one "
                "array")

    def _worker(worker, cids):
        def run():
            for cid in cids:
                # Raw acquire on purpose: the release-after-journal
                # discipline under test IS the pairing.
                buf = pool.acquire((4, 8), "float32")  # riplint: disable=RIP014
                if buf is None:
                    buf = np.zeros((4, 8), dtype="float32")
                elif id(buf) in held:
                    _violate(
                        "staging-no-double-release",
                        f"acquire handed {worker} chunk {cid} a buffer "
                        f"still held by {held[id(buf)]}")
                held[id(buf)] = (worker, cid)
                sched.op_point(label=f"prep+dispatch chunk {cid}")
                if mutation == "early_release":
                    _release_checked(worker, cid, buf)
                    sched.op_point(label=f"journal chunk {cid}")
                    journaled.add((worker, cid))
                else:
                    sched.op_point(label=f"journal chunk {cid}")
                    journaled.add((worker, cid))
                    _release_checked(worker, cid, buf)
                    if mutation == "double_release":
                        _release_checked(worker, cid, buf)
        return run

    tasks = [("w1", _worker("w1", (0, 1))), ("w2", _worker("w2", (2, 3)))]

    def final_check():
        out = []
        if len(journaled) != 4 or held:
            out.append((
                "staging-release-after-journal",
                f"quiesced with {len(journaled)}/4 chunks journaled and "
                f"{len(held)} buffer(s) still held"))
        return out

    return tasks, final_check


def _build_runctx(repo, sched, mutation):
    rmod = load_target(repo, "utils.runctx")
    instrument(rmod, sched)
    sinks = {"jobA": [], "jobB": []}
    global_records = []
    inbox = []
    progress = {"jobs_done": 0}
    inbox_lock = SchedLock(sched, name="inbox")

    def mini_emit(kind, job):
        # Mirrors survey/incidents.py::emit's PR-17 resolution order
        # (context first, process-global sink second) — update with it.
        rec = {"incident": kind, "job": job}
        sink = global_records.append
        ctx = rmod.current()
        if ctx is not None:
            ctx.note_incident(rec)
            if ctx.incident_sink is not None:
                sink = ctx.incident_sink
        sink(rec)

    def _job(job):
        def run():
            ctx = rmod.RunContext(incident_sink=sinks[job].append,
                                  label=job)
            with rmod.activate(ctx):
                mini_emit("chunk_parked", job)
                def emit_remote(j=job):
                    mini_emit("watchdog_timeout", j)
                handed = (emit_remote if mutation == "unwrapped_worker"
                          else rmod.wrap(emit_remote))
                with inbox_lock:
                    inbox.append(handed)
                sched.op_point(label="mid-chunk work")
                mini_emit("device_error", job)
            if rmod.current() is not None:
                _violate("runctx-restore",
                         f"{job}: a context is still installed after "
                         "activate() exited")
            progress["jobs_done"] += 1
        return run

    def _pool_worker():
        while True:
            sched.op_point(
                pred=lambda: bool(inbox) or progress["jobs_done"] >= 2,
                label="poll inbox")
            with inbox_lock:
                item = inbox.pop(0) if inbox else None
            if item is None:
                if progress["jobs_done"] >= 2:
                    return
                continue
            item()
            if rmod.current() is not None:
                _violate("runctx-restore",
                         "pool worker: a handed-off callable leaked its "
                         "context past the call")

    tasks = [("jobA", _job("jobA")), ("jobB", _job("jobB")),
             ("worker", _pool_worker)]

    def final_check():
        out = []
        for rec in global_records:
            out.append((
                "incident-own-journal",
                f"incident {rec['incident']!r} of {rec['job']} landed "
                "in the process-global sink instead of its job's "
                "journal"))
        for job, recs in sorted(sinks.items()):
            stray = [r for r in recs if r["job"] != job]
            if stray:
                out.append((
                    "incident-own-journal",
                    f"{job}'s journal received "
                    f"{[r['incident'] for r in stray]} emitted by "
                    f"{stray[0]['job']}"))
            kinds = [r["incident"] for r in recs if r["job"] == job]
            want = ["chunk_parked", "watchdog_timeout", "device_error"]
            if sorted(kinds) != sorted(want):
                out.append((
                    "incident-own-journal",
                    f"{job}'s journal holds {sorted(kinds)}; expected "
                    f"{sorted(want)}"))
        return out

    return tasks, final_check


def _build_quarantine(repo, sched, mutation):
    incidents = []
    parked = []
    completed = []

    class _Latch:
        """Mirror of survey/integrity.py::IntegrityManager's quarantine
        latch (the idempotence guard + single incident emission) — the
        real manager drags journal/jax imports; update with it."""

        def __init__(self, job):
            self.job = job
            self.quarantined = False

        def quarantine(self, chunk_id):
            if mutation == "drop_guard" or not self.quarantined:
                self.quarantined = True
                incidents.append(
                    ("integrity_quarantine", self.job, chunk_id))

    latches = {"jobA": _Latch("jobA"), "jobB": _Latch("jobB")}
    if mutation == "shared_latch":
        latches["jobB"] = latches["jobA"]
    bad = {("jobA", 1)}
    if mutation == "drop_guard":
        bad.add(("jobA", 2))

    def _job(job):
        def run():
            latch = latches[job]
            for cid in range(3):
                sched.op_point(label=f"chunk {cid} gate")
                # Mirrors the scheduler's park-on-latch check
                # (survey/scheduler.py, quarantine park branch).
                if mutation != "drop_guard" and latch.quarantined:
                    parked.append((job, cid))
                    continue
                sched.op_point(label=f"chunk {cid} dispatch")
                if (job, cid) in bad:
                    latch.quarantine(cid)
                    parked.append((job, cid))
                    continue
                completed.append((job, cid))
        return run

    tasks = [("jobA", _job("jobA")), ("jobB", _job("jobB"))]

    def final_check():
        out = []
        per_job = {}
        for kind, job, cid in incidents:
            per_job[job] = per_job.get(job, 0) + 1
        for job, n in sorted(per_job.items()):
            if n > 1:
                out.append((
                    "quarantine-single-incident",
                    f"{job} emitted {n} integrity_quarantine incidents "
                    "for one latch — the idempotence guard is gone"))
        expected = {("jobA", 1), ("jobA", 2)}
        extra = set(parked) - expected
        missing = expected - set(parked)
        if extra:
            out.append((
                "quarantine-implicated-set",
                f"quarantine parked {sorted(extra)} beyond the "
                "implicated job's post-latch chunks — a healthy "
                "sibling lost its device"))
        if missing:
            out.append((
                "quarantine-implicated-set",
                f"chunk(s) {sorted(missing)} dispatched after the "
                "device was latched suspect instead of parking"))
        return out

    return tasks, final_check


_INV = {
    "no-lost-wakeup": ("RIPS01", "no schedule deadlocks: every dropped "
                                 "notify or stuck waiter is reported"),
    "termination": ("RIPS01", "every schedule quiesces within the "
                              "decision budget"),
    "gate-mutual-exclusion": ("RIPS02", "at most one job holds the "
                                        "device turn"),
    "drain-termination": ("RIPS02", "drain quiesces every job as "
                                    "completed or parked-resumable"),
    "staging-no-double-release": ("RIPS03", "no staging buffer is freed "
                                            "twice or handed out while "
                                            "held"),
    "staging-release-after-journal": ("RIPS03", "staging buffers "
                                                "recycle only after the "
                                                "chunk's journal "
                                                "record"),
    "incident-own-journal": ("RIPS04", "every incident lands in its own "
                                       "job's journal under "
                                       "concurrency"),
    "runctx-restore": ("RIPS04", "run contexts restore on every "
                                 "install/activate/wrap path"),
    "fair-share-pick": ("RIPS05", "every turn grant is minimal in the "
                                  "fair-share key (no tenant "
                                  "starvation)"),
    "charge-conservation": ("RIPS05", "turn seconds charged to the "
                                      "queue and the tenant table "
                                      "agree"),
    "quarantine-single-incident": ("RIPS06", "one quarantine latch "
                                             "emits one incident"),
    "quarantine-implicated-set": ("RIPS06", "quarantine parks exactly "
                                            "the implicated job's "
                                            "post-latch chunks"),
}

# SARIF rule metadata (one rule per invariant family), reused by
# tools/ripsched.py --format sarif through riplint's writer.
SARIF_RULES = (
    ("RIPS01", "sched-liveness",
     "no lost wakeups or divergence in any explored schedule"),
    ("RIPS02", "sched-drain",
     "fair-share turns are mutually exclusive and drain terminates "
     "with zero non-terminal records"),
    ("RIPS03", "sched-staging",
     "staging buffers: no double release, release only after the "
     "chunk's journal record"),
    ("RIPS04", "sched-runctx",
     "incidents route to their own job's journal; contexts restore on "
     "every path"),
    ("RIPS05", "sched-fairshare",
     "turn grants are fair-share minimal and charges are conserved"),
    ("RIPS06", "sched-quarantine",
     "the integrity quarantine latch parks exactly the implicated "
     "set, once"),
)


def _invariants(ids):
    return tuple((i, _INV[i][1]) for i in ids)


MODELS = {
    "fairshare": ModelSpec(
        "fairshare",
        "REAL FairShareQueue + TenantTable: three jobs across two "
        "tenants take chunk turns while a drain lands",
        ("riptide_tpu/serve/queue.py", "riptide_tpu/serve/tenants.py"),
        _invariants(("no-lost-wakeup", "termination",
                     "gate-mutual-exclusion", "drain-termination",
                     "fair-share-pick", "charge-conservation")),
        {"drop_notify": "end() forgets notify_all — waiters park "
                        "forever (lost wakeup)",
         "unfair_pick": "_pick degrades to FIFO-by-submission — "
                        "starves the lighter tenant",
         "drop_charge": "end() skips TenantTable.charge — quota "
                        "enforcement diverges from reality"},
        _build_fairshare,
    ),
    "staging": ModelSpec(
        "staging",
        "REAL _StagingPool (AST-extracted from search/engine.py): two "
        "prep workers recycle wire buffers under the "
        "release-after-journal discipline",
        ("riptide_tpu/search/engine.py",),
        _invariants(("no-lost-wakeup", "termination",
                     "staging-no-double-release",
                     "staging-release-after-journal")),
        {"double_release": "a chunk's buffers are released twice — two "
                           "later acquires alias one array",
         "early_release": "buffers released before the chunk's journal "
                          "record — a retry re-ship reads recycled "
                          "memory"},
        _build_staging,
    ),
    "runctx": ModelSpec(
        "runctx",
        "REAL utils/runctx.py: two jobs activate contexts and hand "
        "emitting work to a shared pool worker via wrap()",
        ("riptide_tpu/utils/runctx.py",),
        _invariants(("no-lost-wakeup", "termination",
                     "incident-own-journal", "runctx-restore")),
        {"unwrapped_worker": "work handed to the pool without "
                             "runctx.wrap — its incidents land in the "
                             "process-global sink"},
        _build_runctx,
    ),
    "quarantine": ModelSpec(
        "quarantine",
        "mirrored IntegrityManager quarantine latch + scheduler park "
        "loop: one job's device goes suspect mid-run beside a healthy "
        "sibling",
        ("riptide_tpu/survey/integrity.py",
         "riptide_tpu/survey/scheduler.py"),
        _invariants(("no-lost-wakeup", "termination",
                     "quarantine-single-incident",
                     "quarantine-implicated-set")),
        {"shared_latch": "both jobs share one latch object — a "
                         "sibling's chunks park for a device it never "
                         "touched",
         "drop_guard": "park check and idempotence guard dropped — "
                       "post-latch chunks dispatch and re-emit"},
        _build_quarantine,
    ),
}


# -- schedule IDs ------------------------------------------------------------

def format_schedule_id(model, mutation, digits):
    tag = f"{model}+{mutation}" if mutation else model
    return f"{tag}:{digits}"


def parse_schedule_id(schedule_id):
    """``(model, mutation_or_None, digit_tuple)`` from a schedule ID;
    raises ValueError with a usable message on malformed input."""
    if ":" not in schedule_id:
        raise ValueError(
            f"malformed schedule id {schedule_id!r}: expected "
            "model[+mutation]:digits")
    tag, _, digits = schedule_id.partition(":")
    model, _, mutation = tag.partition("+")
    mutation = mutation or None
    if model not in MODELS:
        raise ValueError(
            f"unknown model {model!r} (known: {sorted(MODELS)})")
    if mutation is not None and mutation not in MODELS[model].mutations:
        raise ValueError(
            f"unknown mutation {mutation!r} for model {model!r} "
            f"(known: {sorted(MODELS[model].mutations)})")
    if digits and not digits.isdigit():
        raise ValueError(
            f"malformed schedule digits {digits!r}: decimal task "
            "indices only")
    return model, mutation, tuple(int(d) for d in digits)


# -- exploration -------------------------------------------------------------

class Violation:
    """One invariant violation with its minimal failing schedule."""

    def __init__(self, model, mutation, invariant, message,
                 schedule_id, trace_lines, preemptions):
        self.model = model
        self.mutation = mutation
        self.invariant = invariant
        self.message = message
        self.schedule_id = schedule_id
        self.trace_lines = list(trace_lines)
        self.preemptions = preemptions

    def render(self):
        lines = [
            f"ripsched VIOLATION [{self.invariant}] in model "
            f"{self.model!r}"
            + (f" (mutation {self.mutation!r})" if self.mutation
               else ""),
            f"  {self.message}",
            f"  minimal failing schedule ({self.preemptions} "
            f"preemption(s)):",
        ]
        lines.extend(self.trace_lines)
        lines.append(f"  replay: python tools/ripsched.py --replay "
                     f"'{self.schedule_id}'")
        return "\n".join(lines)


class ExploreResult:
    def __init__(self, model, mutation, bound, schedules, decisions,
                 capped, violation):
        self.model = model
        self.mutation = mutation
        self.bound = bound
        self.schedules = schedules
        self.decisions = decisions
        self.capped = capped
        self.violation = violation


_ENVFLAGS_MOD = [None]


def _envflags(repo=REPO):
    if _ENVFLAGS_MOD[0] is None:
        path = os.path.join(repo, "riptide_tpu", "utils", "envflags.py")
        spec = importlib.util.spec_from_file_location(
            "riptide_tpu_envflags_for_sched", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _ENVFLAGS_MOD[0] = mod
    return _ENVFLAGS_MOD[0]


def env_default(name, repo=REPO):
    """Registered default/override for a RIPTIDE_SCHED_* flag, via the
    typed envflags registry (loaded standalone, jax-free)."""
    return _envflags(repo).get(name)


def _run_schedule(repo, model, mutation, prefix,
                  max_steps=DEFAULT_MAX_STEPS):
    spec = MODELS[model]
    sched = Scheduler(schedule=prefix, max_steps=max_steps)
    tasks, final_check = spec.build(repo, sched, mutation)
    for name, fn in tasks:
        sched.spawn(name, fn)
    sched.run()
    if sched.violation is None and sched.diverged is None:
        for invariant, message in final_check():
            sched.violation = (invariant, message)
            break
    return sched


def _make_violation(model, mutation, sched, preemptions):
    invariant, message = sched.violation
    return Violation(
        model, mutation, invariant, message,
        format_schedule_id(model, mutation, sched.digits()),
        sched.trace_lines(), preemptions)


def _trace_preemptions(trace):
    """Per-step cumulative preemption counts: step ``i`` preempts when
    it switches away from a task that was still enabled."""
    cum = [0] * (len(trace) + 1)
    for i, (chosen, enabled, _) in enumerate(trace):
        pre = (i > 0 and chosen != trace[i - 1][0]
               and trace[i - 1][0] in enabled)
        cum[i + 1] = cum[i] + (1 if pre else 0)
    return cum


def explore_model(model, mutation=None, bound=None, seed=None,
                  max_schedules=None, repo=REPO, log=None):
    """Iterative preemption-bounded DFS over ``model``'s schedules:
    every schedule with exactly ``b`` preemptions is run once for
    ``b = 0..bound`` (expansion prefixes are filed by their exact
    preemption count, so no schedule repeats across bounds), and the
    first violation — minimal in preemptions by construction — stops
    the search with its replayable schedule ID."""
    if model not in MODELS:
        raise ValueError(
            f"unknown model {model!r} (known: {sorted(MODELS)})")
    if mutation is not None and mutation not in MODELS[model].mutations:
        raise ValueError(
            f"unknown mutation {mutation!r} for model {model!r} "
            f"(known: {sorted(MODELS[model].mutations)})")
    if bound is None:
        bound = int(env_default("RIPTIDE_SCHED_BOUND", repo))
    if seed is None:
        seed = int(env_default("RIPTIDE_SCHED_SEED", repo))
    if max_schedules is None:
        max_schedules = DEFAULT_MAX_SCHEDULES
    rng = random.Random(seed)
    pending = {b: [] for b in range(bound + 1)}
    pending[0].append(())
    schedules = decisions = 0
    capped = False
    for b in range(bound + 1):
        stack = pending[b]
        while stack:
            if max_schedules and schedules >= max_schedules:
                capped = True
                if log is not None:
                    log(f"ripsched: {model}"
                        + (f"+{mutation}" if mutation else "")
                        + f": schedule cap {max_schedules} reached at "
                        f"bound {b} — coverage is BOUNDED, not "
                        "exhaustive (raise --max-schedules)")
                break
            prefix = stack.pop()
            sched = _run_schedule(repo, model, mutation, prefix)
            schedules += 1
            decisions += len(sched.trace)
            if sched.diverged is not None:
                # A prefix replays deterministically, so divergence
                # means the model itself went nondeterministic — a
                # harness bug worth failing loudly on.
                raise RuntimeError(
                    f"model {model!r} diverged at step {sched.diverged} "
                    f"replaying its own prefix {prefix!r}")
            if sched.violation is not None:
                return ExploreResult(
                    model, mutation, bound, schedules, decisions,
                    capped,
                    _make_violation(
                        model, mutation, sched,
                        _trace_preemptions(sched.trace)[-1]))
            choices = [c for c, _, _ in sched.trace]
            cum = _trace_preemptions(sched.trace)
            for i in range(len(prefix), len(sched.trace)):
                _, enabled, _ = sched.trace[i]
                alts = [a for a in enabled if a != choices[i]]
                rng.shuffle(alts)
                for alt in alts:
                    extra = (i > 0 and alt != choices[i - 1]
                             and choices[i - 1] in enabled)
                    total = cum[i] + (1 if extra else 0)
                    if total <= bound:
                        pending[total].append(
                            tuple(choices[:i]) + (alt,))
        if capped:
            break
    return ExploreResult(model, mutation, bound, schedules, decisions,
                         capped, None)


class ReplayResult:
    def __init__(self, schedule_id, model, mutation, trace_lines,
                 violation, diverged):
        self.schedule_id = schedule_id
        self.model = model
        self.mutation = mutation
        self.trace_lines = list(trace_lines)
        self.violation = violation
        self.diverged = diverged

    def render(self):
        head = [f"ripsched replay {self.schedule_id}"]
        head.extend(self.trace_lines)
        if self.diverged is not None:
            head.append(f"  DIVERGED at step {self.diverged}: the "
                        "recorded digit is not enabled (model changed "
                        "since recording?)")
        elif self.violation is not None:
            head.append(self.violation.render())
        else:
            head.append("  clean: no invariant violated on this "
                        "schedule")
        return "\n".join(head)


def replay(schedule_id, repo=REPO, max_steps=DEFAULT_MAX_STEPS):
    """Re-execute one recorded schedule exactly. Deterministic: the
    same ID renders a byte-identical trace, so a violation's repro is
    stable across machines and runs."""
    model, mutation, digits = parse_schedule_id(schedule_id)
    sched = _run_schedule(repo, model, mutation, digits,
                          max_steps=max_steps)
    violation = None
    if sched.violation is not None:
        violation = _make_violation(
            model, mutation, sched, _trace_preemptions(sched.trace)[-1])
    return ReplayResult(schedule_id, model, mutation,
                        sched.trace_lines(), violation, sched.diverged)


def sarif_rule_of(invariant):
    """The RIPS rule id an invariant reports under (SARIF output)."""
    return _INV[invariant][0]


def spec_doc():
    """The machine-readable invariant spec pinned in
    ``tools/ripsched_invariants.json``: model targets, invariants and
    mutations. The CLI refuses to run when the pinned file drifts from
    this registry (``--write-specs`` re-pins), so the checked-in spec
    — which the riplint cache tracks — always names what `make
    ripsched` actually proves."""
    return {
        "version": 1,
        "models": {
            name: {
                "description": spec.description,
                "targets": list(spec.targets),
                "invariants": [
                    {"id": i, "rule": _INV[i][0], "description": d}
                    for i, d in spec.invariants
                ],
                "mutations": dict(sorted(spec.mutations.items())),
            }
            for name, spec in sorted(MODELS.items())
        },
    }
