"""
RIP008 — obs (tracing) discipline.

The span tracer is threaded through every hot path of the survey
pipeline, so its misuse modes are throughput or correctness bugs:

* **span() only as a context manager** — a ``span(...)`` call that is
  not the context expression of a ``with`` statement risks a manual
  ``__enter__`` without a guaranteed ``__exit__``, which leaks the
  per-thread span stack entry and corrupts nesting for every later
  span on that thread;
* **no tracing inside jit bodies or Pallas kernel closures** — spans
  time *host-side* phases on wall clocks; inside a traced body the
  call runs at trace time (measuring compilation, not execution) and
  inside a kernel closure it is host nondeterminism baked into a
  cached executable (the RIP005 failure class). Device-side timelines
  belong to the ``jax.profiler`` exporter;
* **every observability flag is registered** — ``RIPTIDE_TRACE_*`` /
  ``RIPTIDE_PROM_*``-family tokens in package sources must name
  entries of the
  typed ``utils/envflags.py`` registry (RIP003 polices reads; this
  closes the gap for names that only appear in docs strings or are
  read through indirection).

``riptide_tpu/obs/trace.py`` itself is exempt: it *implements* the
span protocol (``Span.__enter__``/``__exit__`` live there by
definition).
"""
import ast
import re

from .core import Analyzer, Finding, dotted, walk_functions
from .env_flags import REGISTRY_REL, load_registry
from .host_sync import _is_jit_decorated

__all__ = ["ObsDisciplineAnalyzer"]

# The module that implements the span protocol (and may therefore
# mention manual enter/exit) — everything else must follow the rules.
_EXEMPT = ("riptide_tpu/obs/trace.py",)

# Tracing entry points that must never run inside traced/kernel code.
_TRACE_CALLS = {"span", "get_tracer", "enable", "disable"}

# A token ending in "_" is a docs-string wildcard ("RIPTIDE_TRACE_*"),
# not a flag name.
_OBS_TOKEN = re.compile(r"RIPTIDE_(?:TRACE|PROM)[A-Z0-9_]*")


def _span_calls(tree):
    """Every Call node whose callee leaf-name is ``span``."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            name = dotted(node.func) or ""
            if name.split(".")[-1] == "span":
                yield node


def _with_context_exprs(tree):
    """ids of every ``with``-item context expression in the module."""
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                out.add(id(item.context_expr))
    return out


def _kernel_closure_functions(ctx):
    """{name: FunctionDef} of every module function reachable from a
    Pallas kernel root (the function handed to ``pallas_call``) — the
    same closure walk RIP005 uses for its nondeterminism check."""
    from .pallas_layout import PallasLayoutAnalyzer

    roots = PallasLayoutAnalyzer()._kernel_roots(ctx)
    by_leaf = {}
    for qual, fn in walk_functions(ctx.tree):
        by_leaf.setdefault(qual.split(".")[-1], fn)
    reach = {}
    frontier = [r for r in roots if r in by_leaf]
    while frontier:
        name = frontier.pop()
        if name in reach:
            continue
        reach[name] = by_leaf[name]
        for node in ast.walk(by_leaf[name]):
            if isinstance(node, ast.Call):
                callee = (dotted(node.func) or "").split(".")[-1]
                if callee in by_leaf and callee not in reach:
                    frontier.append(callee)
    return reach


class ObsDisciplineAnalyzer(Analyzer):
    rule = "RIP008"
    name = "obs-discipline"
    description = ("span() only as a context manager, no tracing calls "
                   "inside jit bodies or Pallas kernel closures, every "
                   "RIPTIDE_TRACE_*/RIPTIDE_PROM_* flag registered")

    def __init__(self):
        self._registry_flags = None

    def begin(self, repo):
        self._registry_flags = None

    def _flags(self, repo):
        if self._registry_flags is None:
            try:
                self._registry_flags = set(load_registry(repo).FLAGS)
            except Exception:
                # RIP003 reports a broken registry; don't double up.
                self._registry_flags = frozenset()
        return self._registry_flags

    def run(self, ctx):
        if ctx.relpath in _EXEMPT:
            return []
        findings = []

        # -- span() must be a with-item ---------------------------------
        as_context = _with_context_exprs(ctx.tree)
        flagged = set()
        for call in _span_calls(ctx.tree):
            if id(call) not in as_context:
                flagged.add(id(call))
                findings.append(Finding.at(
                    ctx, call, self.rule,
                    "`span(...)` used outside a `with` statement — a "
                    "manual __enter__ without a guaranteed __exit__ "
                    "leaks the per-thread span stack; write "
                    "`with span(...):`",
                ))

        # -- no tracing inside jit bodies / kernel closures --------------
        def scan_scope(fn, where):
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call) or id(node) in flagged:
                    continue
                name = dotted(node.func) or ""
                if name.split(".")[-1] in _TRACE_CALLS:
                    flagged.add(id(node))
                    findings.append(Finding.at(
                        ctx, node, self.rule,
                        f"tracing call `{name}` inside {where} — spans "
                        "time host-side phases only (in traced code "
                        "this measures trace time; device timelines "
                        "are the jax.profiler exporter's job)",
                    ))

        kernel_fns = _kernel_closure_functions(ctx)
        for qual, fn in walk_functions(ctx.tree):
            if _is_jit_decorated(fn):
                scan_scope(fn, f"jit body `{qual}`")
        for name, fn in sorted(kernel_fns.items()):
            scan_scope(fn, f"Pallas kernel closure `{name}`")

        # -- observability flag tokens must be registered ----------------
        if ctx.relpath != REGISTRY_REL:
            flags = self._flags(ctx.repo)
            seen_lines = set()
            for m in _OBS_TOKEN.finditer(ctx.source):
                token = m.group(0)
                if token.endswith("_") or token in flags:
                    continue
                line = ctx.source.count("\n", 0, m.start()) + 1
                if (token, line) in seen_lines:
                    continue
                seen_lines.add((token, line))
                findings.append(Finding(
                    ctx.relpath, line, 0, self.rule,
                    f"observability flag {token!r} is not in the "
                    "utils/envflags.py registry — declare it (type, "
                    "default, help) so the tracing/exposition surface "
                    "stays enumerable",
                ))
        return findings
