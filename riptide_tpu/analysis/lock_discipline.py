"""
RIP004 — lock and thread discipline in the threading modules.

The survey's liveness machinery exists because *unbounded waits kill
long campaigns* (Parent et al. 2018's pipeline-reliability posture):
a blocking call made while holding a lock turns every other thread
needing that lock into a hostage of the slow operation; an untimed
``join()`` / ``Event.wait()`` blocks forever on a wedged thread; a
thread without an explicit daemon flag inherits whatever the default
is, which decides whether a hung worker can block interpreter exit.

Scoped to the six modules that own threads or locks (ISSUE 5):
``survey/liveness.py``, ``survey/faults.py``, ``survey/metrics.py``,
``utils/exec_cache.py``, ``ops/ffa_kernel.py``, ``native/__init__.py``.

Checks:

* **no blocking call under a lock** — inside a ``with <lock>:`` body:
  ``time.sleep``, ``subprocess.*``, untimed ``join()`` / ``wait()``,
  ``.acquire()`` of another lock, and the known-blocking local helpers
  (``_build``, ``load_or_compile_exec`` — the native/kernel build
  paths). Intentional build-serialisation locks go in the baseline
  with their justification;
* **untimed join** — ``.join()`` with no arguments anywhere in scope
  (a zero-argument join cannot be ``str.join``; ``Thread.join()``
  without a timeout waits forever);
* **untimed wait** — ``.wait()`` with no arguments (``Event.wait()``
  / ``Condition.wait()`` without a timeout);
* **implicit daemon flag** — ``threading.Thread(...)`` without an
  explicit ``daemon=`` keyword.
"""
import ast

from .core import Analyzer, Finding, dotted

__all__ = ["LockDisciplineAnalyzer", "MODULES"]

MODULES = {
    "riptide_tpu/survey/liveness.py",
    "riptide_tpu/survey/faults.py",
    "riptide_tpu/survey/metrics.py",
    "riptide_tpu/utils/exec_cache.py",
    "riptide_tpu/ops/ffa_kernel.py",
    "riptide_tpu/native/__init__.py",
}

# Local helpers known to block for seconds-to-minutes (compiler runs).
_BLOCKING_HELPERS = {"_build", "load_or_compile_exec"}


def _is_lockish(node):
    """True for a with-item context that names a lock (`self._lock`,
    `_lru_lock`, ...)."""
    name = dotted(node)
    return name is not None and "lock" in name.split(".")[-1].lower()


def _blocking_reason(node):
    """Why a call inside a lock-held region is considered blocking, or
    None."""
    if not isinstance(node, ast.Call):
        return None
    name = dotted(node.func) or ""
    leaf = name.split(".")[-1]
    if name.endswith("time.sleep") or name == "sleep" \
            or leaf == "_sleep":
        return "sleeps"
    if name.startswith("subprocess."):
        return "runs a subprocess"
    if leaf in _BLOCKING_HELPERS:
        return "invokes a known-blocking build/compile helper"
    if isinstance(node.func, ast.Attribute) and not node.args \
            and not node.keywords:
        if node.func.attr == "join":
            return "joins a thread without a timeout"
        if node.func.attr == "wait":
            return "waits without a timeout"
        if node.func.attr == "acquire":
            return "acquires another lock (ordering deadlock risk)"
    return None


class LockDisciplineAnalyzer(Analyzer):
    rule = "RIP004"
    name = "lock-discipline"
    description = ("no blocking call while holding a lock, no untimed "
                   "join()/wait(), explicit Thread daemon flags in the "
                   "threading modules")

    def __init__(self, modules=None):
        self.modules = MODULES if modules is None else modules
        self._seen_modules = set()

    def begin(self, repo):
        self._seen_modules = set()

    def finalize(self, repo, contexts):
        """Staleness guard: a scoped threading module that vanished
        (moved/renamed) must fail loudly, not silently unscope the
        lint."""
        return [
            Finding(rel, 1, 0, self.rule,
                    "threading module missing from the package — the "
                    "lock-discipline scope list (analysis/"
                    "lock_discipline.py MODULES) is stale; update it")
            for rel in sorted(set(self.modules) - self._seen_modules)
        ]

    def run(self, ctx):
        if ctx.relpath not in self.modules:
            return []
        self._seen_modules.add(ctx.relpath)
        findings = []

        # Blocking calls under a held lock.
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            if not any(_is_lockish(item.context_expr)
                       or (isinstance(item.context_expr, ast.Call)
                           and _is_lockish(item.context_expr.func))
                       for item in node.items):
                continue
            for inner in node.body:
                for sub in ast.walk(inner):
                    reason = _blocking_reason(sub)
                    if reason is not None:
                        findings.append(Finding.at(
                            ctx, sub, self.rule,
                            f"call {reason} while a lock is held — every "
                            "other thread needing the lock stalls behind "
                            "it; move the blocking work outside the "
                            "critical section",
                        ))

        # Untimed join()/wait() and implicit daemon flags, module-wide.
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Attribute) and not node.args \
                    and not node.keywords:
                if f.attr == "join":
                    findings.append(Finding.at(
                        ctx, node, self.rule,
                        "`.join()` without a timeout waits forever on a "
                        "wedged thread — pass a timeout and handle the "
                        "still-alive case",
                    ))
                elif f.attr == "wait":
                    findings.append(Finding.at(
                        ctx, node, self.rule,
                        "`.wait()` without a timeout waits forever — "
                        "pass a timeout (the liveness layer exists to "
                        "bound every wait)",
                    ))
            name = dotted(f) or ""
            if name in ("threading.Thread", "Thread"):
                if not any(kw.arg == "daemon" for kw in node.keywords):
                    findings.append(Finding.at(
                        ctx, node, self.rule,
                        "`threading.Thread` without an explicit "
                        "`daemon=` — whether a hung worker can block "
                        "interpreter exit must be a decision, not a "
                        "default",
                    ))
        # One finding per site: an untimed join/wait inside a lock-held
        # region would otherwise be reported by both walks (and nested
        # lock-withs re-scan inner bodies). First wins — the under-lock
        # message is the more specific one.
        seen, out = set(), []
        for f in findings:
            key = (f.line, f.col)
            if key not in seen:
                seen.add(key)
                out.append(f)
        return out
