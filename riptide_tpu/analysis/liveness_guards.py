"""
RIP007 — bounded-collective discipline (ported from
``tools/check_liveness_guards.py``, which remains as a thin shim).

Every ``multihost_utils`` collective call site in ``riptide_tpu/``
goes through the liveness layer's bounded-wait wrappers
(``bounded_allgather`` / ``barrier_with_timeout``), so a dead peer
cannot deadlock the run; import bindings that would evade the
attribute check are violations at the import itself, and ZERO wrapped
call sites means the wrappers were refactored away and the lint has
gone vacuous (also a failure). Same AST logic as the original tool,
now emitting framework findings.
"""
import ast
import os

from .core import Analyzer, Finding

__all__ = ["LivenessGuardAnalyzer", "ALLOWED", "check_file", "check"]

# relpath -> function names allowed to call multihost_utils
ALLOWED = {
    "riptide_tpu/survey/liveness.py":
        {"bounded_allgather", "barrier_with_timeout"},
}

_WRAPPER_HOME = "riptide_tpu/survey/liveness.py"


def _is_multihost_attr(node):
    """True for an attribute access rooted at a name (or attribute)
    called ``multihost_utils`` — covers ``multihost_utils.x`` and
    ``jax.experimental.multihost_utils.x``."""
    if not isinstance(node, ast.Attribute):
        return False
    v = node.value
    if isinstance(v, ast.Name):
        return v.id == "multihost_utils"
    if isinstance(v, ast.Attribute):
        return v.attr == "multihost_utils"
    return False


def _call_sites(tree):
    """Sites that can reach a collective, as ``(lineno, enclosing
    function name or None, kind)`` — see the original tool's docstring
    for the call/import taxonomy."""
    sites = []

    def visit(node, fn):
        name = fn
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            name = node.name
        if isinstance(node, ast.Call) and _is_multihost_attr(node.func):
            sites.append((node.lineno, name, "call"))
        elif isinstance(node, ast.ImportFrom):
            if node.module \
                    and node.module.split(".")[-1] == "multihost_utils":
                sites.append((node.lineno, name, "import"))
            else:
                for a in node.names:
                    if a.name == "multihost_utils" and a.asname not in (
                            None, "multihost_utils"):
                        sites.append((node.lineno, name, "import"))
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name.split(".")[-1] == "multihost_utils" \
                        and a.asname is not None:
                    sites.append((node.lineno, name, "import"))
        for child in ast.iter_child_nodes(node):
            visit(child, name)

    visit(tree, None)
    return sites


def check_tree(tree, rel, allowed):
    """Structured violations for one parsed module: ``(violations,
    wrapped)`` where violations are ``(lineno, message)`` and
    ``wrapped`` counts collective calls inside allowed wrappers."""
    violations, wrapped = [], 0
    for lineno, fn, kind in _call_sites(tree):
        if fn is not None and fn in allowed.get(rel, ()):
            if kind == "call":
                wrapped += 1
            continue
        what = ("raw multihost_utils collective" if kind == "call"
                else "multihost_utils import that evades the call check")
        violations.append((
            lineno,
            f"{what} "
            f"{'in ' + fn + '()' if fn else 'at module level'} — route it "
            "through riptide_tpu.survey.liveness (bounded_allgather / "
            "barrier_with_timeout) so a dead peer cannot deadlock the run",
        ))
    return violations, wrapped


def check_file(path, rel, allowed):
    """Back-compat string API; second return value counts call sites
    inside allowed wrappers."""
    with open(path) as fobj:
        tree = ast.parse(fobj.read(), filename=path)
    violations, wrapped = check_tree(tree, rel, allowed)
    return [f"{rel}:{lineno}: {msg}" for lineno, msg in violations], wrapped


VACUOUS_MESSAGE = (
    "no multihost_utils call found inside the allowed liveness "
    "wrappers — the lint has gone vacuous (were "
    "bounded_allgather/barrier_with_timeout refactored away? "
    "update the liveness-guard allowlist)"
)


def check(repo, allowed=None):
    """All violations (strings) across ``riptide_tpu/``;
    vacuous-lint guard included."""
    allowed = ALLOWED if allowed is None else allowed
    # Accept OS-path keys too (the original tool used os.path.join).
    allowed = {k.replace(os.sep, "/"): v for k, v in allowed.items()}
    package = os.path.join(repo, "riptide_tpu")
    violations, wrapped_total = [], 0
    for dirpath, dirnames, filenames in os.walk(package):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            rel = os.path.relpath(path, repo).replace(os.sep, "/")
            v, wrapped = check_file(path, rel, allowed)
            violations.extend(v)
            wrapped_total += wrapped
    if wrapped_total == 0:
        violations.append(VACUOUS_MESSAGE)
    return violations


class LivenessGuardAnalyzer(Analyzer):
    rule = "RIP007"
    name = "liveness-guards"
    description = ("multihost_utils collectives route through the "
                   "liveness layer's bounded-wait wrappers")

    def __init__(self, allowed=None):
        self.allowed = ALLOWED if allowed is None else allowed
        self._wrapped = 0

    def begin(self, repo):
        self._wrapped = 0

    def run(self, ctx):
        violations, wrapped = check_tree(ctx.tree, ctx.relpath,
                                         self.allowed)
        self._wrapped += wrapped
        return [
            Finding(ctx.relpath, lineno, 0, self.rule, msg)
            for lineno, msg in violations
        ]

    def finalize(self, repo, contexts):
        if self._wrapped == 0:
            return [Finding(_WRAPPER_HOME, 1, 0, self.rule,
                            VACUOUS_MESSAGE)]
        return []
