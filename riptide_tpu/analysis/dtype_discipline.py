"""
RIP002 — dtype discipline in the numeric core.

The reproduction's numerics rest on two dtype rules (PAPER.md §L0,
docs/architecture.md): sample data is float32, accumulators (prefix
sums, downsample reductions) are float64, and nothing may silently
promote through numpy's float64 default or jax's weak types. The
checks are scoped to the numeric core (``ops/`` and the engine/peaks
device paths) where a silent dtype change is a *wrong numbers* bug,
not a style issue:

* array creation (``zeros`` / ``ones`` / ``empty`` / ``full`` on
  np/jnp, plus ``jnp.arange``) must name its dtype — numpy's silent
  float64 default either doubles the wire or downcasts later, and
  which one happens depends on call-site luck;
* ``cumsum`` (the accumulator primitive) must pass ``dtype=`` or
  ``out=`` — the float64 accumulator rule made explicit at every site;
* ``jnp.array`` / ``jnp.asarray`` of a Python literal must name its
  dtype (weak-type promotion otherwise depends on what the value later
  meets).
"""
import ast

from .core import Analyzer, Finding

__all__ = ["DtypeDisciplineAnalyzer", "SCOPE"]

SCOPE_PREFIXES = ("riptide_tpu/ops/",)
SCOPE = {
    "riptide_tpu/search/engine.py",
    "riptide_tpu/search/peaks_device.py",
}

_CREATE_MIN_ARGS = {"zeros": 2, "ones": 2, "empty": 2, "full": 3}
_NP_NAMES = {"np", "numpy", "jnp", "onp"}


def _np_call(node, attrs):
    """The called attr name when ``node`` is ``np.<attr>(...)`` /
    ``jnp.<attr>(...)`` with attr in ``attrs``; else None."""
    if not isinstance(node, ast.Call):
        return None
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr in attrs \
            and isinstance(f.value, ast.Name) and f.value.id in _NP_NAMES:
        return f.attr, f.value.id
    return None


def _has_dtype(node, min_args):
    if len(node.args) >= min_args:
        return True
    return any(kw.arg in ("dtype", "out") for kw in node.keywords)


def _literal_arg(node):
    """True when the first argument is a Python literal (number, or a
    list/tuple display) — the weak-type promotion case. Arrays passed
    by name keep their dtype and are fine without one."""
    if not node.args:
        return False
    a = node.args[0]
    if isinstance(a, ast.Constant) and isinstance(a.value, (int, float)):
        return True
    return isinstance(a, (ast.List, ast.Tuple))


class DtypeDisciplineAnalyzer(Analyzer):
    rule = "RIP002"
    name = "dtype-discipline"
    description = ("float64 accumulator rule and explicit dtypes in the "
                   "numeric core (ops/ and the engine/peaks paths)")

    def __init__(self, scope=None, scope_prefixes=None):
        self.scope = SCOPE if scope is None else scope
        self.scope_prefixes = (SCOPE_PREFIXES if scope_prefixes is None
                               else scope_prefixes)
        self._seen_modules = set()

    def begin(self, repo):
        self._seen_modules = set()

    def finalize(self, repo, contexts):
        """Staleness guard on the explicitly-listed scope modules (the
        prefix scopes track directory moves on their own)."""
        return [
            Finding(rel, 1, 0, self.rule,
                    "scoped numeric-core module missing from the "
                    "package — the dtype-discipline scope list "
                    "(analysis/dtype_discipline.py SCOPE) is stale; "
                    "update it")
            for rel in sorted(set(self.scope) - self._seen_modules)
        ]

    def _in_scope(self, relpath):
        return relpath in self.scope or any(
            relpath.startswith(p) for p in self.scope_prefixes
        )

    def run(self, ctx):
        if not self._in_scope(ctx.relpath):
            return []
        if ctx.relpath in self.scope:
            self._seen_modules.add(ctx.relpath)
        findings = []
        for node in ast.walk(ctx.tree):
            hit = _np_call(node, set(_CREATE_MIN_ARGS) | {"arange",
                                                          "cumsum",
                                                          "array",
                                                          "asarray"})
            if hit is None:
                continue
            attr, mod = hit
            if attr in _CREATE_MIN_ARGS:
                if not _has_dtype(node, _CREATE_MIN_ARGS[attr]):
                    findings.append(Finding.at(
                        ctx, node, self.rule,
                        f"`{mod}.{attr}` without an explicit dtype in the "
                        "numeric core — numpy defaults to float64 and "
                        "jax to float32; name the dtype so the "
                        "float32-data/float64-accumulator split is "
                        "visible at the call site",
                    ))
            elif attr == "arange" and mod == "jnp":
                if not _has_dtype(node, 99):
                    findings.append(Finding.at(
                        ctx, node, self.rule,
                        "`jnp.arange` without an explicit dtype in the "
                        "numeric core — index dtype must be pinned "
                        "(int32 on device)",
                    ))
            elif attr == "cumsum":
                if not _has_dtype(node, 99):
                    findings.append(Finding.at(
                        ctx, node, self.rule,
                        f"`{mod}.cumsum` without `dtype=`/`out=` — the "
                        "accumulator rule (float64 prefix sums) must be "
                        "explicit at every reduction site",
                    ))
            elif attr in ("array", "asarray") and mod == "jnp":
                if _literal_arg(node) and not _has_dtype(node, 2):
                    findings.append(Finding.at(
                        ctx, node, self.rule,
                        f"`jnp.{attr}` of a Python literal without a "
                        "dtype — weak-type promotion makes the result "
                        "dtype depend on downstream context",
                    ))
        return findings
