"""
RIP010 — record-schema conformance across the append-only formats.

The repo now has three JSONL record families — the survey journal
(header / chunk / parked / metrics / incident records plus heartbeat
sidecars), the perf ledger and the per-chunk timing blocks — whose
*writers* and *readers* live in different packages (journal.py and
incidents.py write what report.py, rtop.py and the scheduler's resume
path read). Nothing ties the two halves together at runtime: a renamed
writer key silently turns every reader of it into a ``.get()`` default,
and a reader expecting a kind no writer emits filters forever on
nothing. This analyzer closes the loop statically:

* **writer extraction** — for each configured writer function, the
  string keys of every dict literal it builds (plus ``var["k"] = ...``
  subscript-assign and ``var.setdefault("k", ...)`` adds on the same
  names), grouped into a record *family*: the literal ``"kind"`` value
  when present, else the family the spec declares (``heartbeat``,
  ``ledger``, ``timing``);
* **reader extraction** — for each configured reader function, every
  ``X.get("k")`` / ``X["k"]`` string-key access, and every *kind
  consumption*: a literal compared against ``.get("kind")`` /
  ``["kind"]`` (directly or through a one-step local binding);
* **checks** — a key read but written by no writer (and absent from
  the readers' own locally-built dict vocabulary and the versioned
  :data:`RECORD_ALLOWLIST`) is an error at the read site; a kind
  consumed but never emitted is an error at the comparison; a writer
  whose record dict is later merged with a run decomposition
  (``row.update(decomposition ...)``) must not literally name any
  ``DECOMPOSITION_KEYS`` (extracted from ``obs/schema.py``) — the
  merge would silently clobber one side.

The allowlist is **versioned**: each entry documents a pre-PR-8/9
backward-compat read (a key old journals carry that no current writer
emits) with the reason it must stay readable. Bump ``version`` when an
entry set changes so reviews see allowlist growth explicitly.

Readers outside the package (``tools/rtop.py``) are parsed by this
analyzer directly; their findings baseline via the path-only entry
form, like docs drift.
"""
import ast
import os

from .core import Analyzer, Finding, ModuleContext, walk_functions

__all__ = ["RecordSchemaAnalyzer", "WRITER_SPECS", "READER_SPECS",
           "RECORD_ALLOWLIST"]

SCHEMA_REL = "riptide_tpu/obs/schema.py"

# (relpath, function qual, declared family or None = take the literal
# "kind" value of each dict).  These are the record EMISSION points —
# every fsync'd append traces back to one of them.
WRITER_SPECS = (
    ("riptide_tpu/survey/journal.py", "SurveyJournal.write_header", None),
    ("riptide_tpu/survey/journal.py", "SurveyJournal.record_chunk", None),
    ("riptide_tpu/survey/journal.py", "SurveyJournal.record_parked", None),
    ("riptide_tpu/survey/journal.py", "SurveyJournal.record_metrics",
     None),
    ("riptide_tpu/survey/journal.py", "SurveyJournal.record_incident",
     "incident"),
    ("riptide_tpu/survey/journal.py", "SurveyJournal.record_alert",
     "alert"),
    ("riptide_tpu/survey/journal.py", "SurveyJournal.heartbeat",
     "heartbeat"),
    ("riptide_tpu/survey/incidents.py", "emit", "incident"),
    ("riptide_tpu/obs/ledger.py", "make_row", "ledger"),
    # The alert engine's fire/resolve record (PR 14): journaled
    # verbatim by record_alert and consumed by report/rtop/rwatch.
    ("riptide_tpu/obs/alerts.py", "AlertEngine._event", "alert"),
    # The per-process fleet snapshot sidecar (PR 14): written by
    # obs/fleet.py, merged by report.read_fleet/merge_fleet.
    ("riptide_tpu/obs/fleet.py", "snapshot", "fleet"),
    # The live signal vector the alert rules evaluate (PR 14): built
    # by the reader side but CONSUMED as a record by the rule engine
    # and rwatch, so its keys are part of the checked schema.
    ("riptide_tpu/obs/report.py", "watch_snapshot", "watch"),
    ("riptide_tpu/obs/schema.py", "chunk_timing", "timing"),
    ("riptide_tpu/obs/schema.py", "decomposition", "ledger"),
    # The chunk record's predicted-vs-actual peak-HBM block (PR 12).
    ("riptide_tpu/obs/schema.py", "hbm_block", "hbm"),
    # The chunk record's result-integrity block (PR 18): Ring 1
    # digests + shadow-probe provenance, merged via `extra=`.
    ("riptide_tpu/obs/schema.py", "integrity_block", "integrity"),
    # Provenance merged in through `extra=` at the call sites.
    ("riptide_tpu/survey/scheduler.py", "SurveyScheduler._run", "ledger"),
    ("riptide_tpu/parallel/multihost.py", "run_search_multihost",
     "chunk"),
    # Chrome trace / platform blocks the report side parses back.
    ("riptide_tpu/obs/chrome.py", "chrome_events", "trace"),
    ("riptide_tpu/obs/chrome.py", "write_chrome_trace", "trace"),
    ("riptide_tpu/obs/chrome.py", "merge_chrome_traces", "trace"),
    ("riptide_tpu/search/engine.py", "device_fingerprint", "platform"),
    # The survey service's job-registry event (PR 16): the ONE builder
    # of jobs.jsonl records, consumed by report.py's job table, rtop's
    # serve view and the daemon's own restart replay.
    ("riptide_tpu/serve/daemon.py", "job_record", "job"),
)

# (relpath, function qual or None = whole module) of the CONSUMPTION
# points: resume, post-run reporting, live monitoring.
READER_SPECS = (
    ("riptide_tpu/survey/journal.py", None),
    ("riptide_tpu/survey/scheduler.py", "SurveyScheduler._run"),
    ("riptide_tpu/survey/liveness.py", "PeerLivenessMonitor.partial_chunks"),
    ("riptide_tpu/obs/report.py", None),
    ("tools/rtop.py", None),
    ("tools/rwatch.py", None),
)

# Versioned backward-compat allowlist: keys readers must keep accepting
# although no current writer emits them (or the writer is outside the
# statically extractable surface). Each entry carries its why; bump the
# version whenever the set changes so the diff is a deliberate act.
RECORD_ALLOWLIST = {
    "version": 2,
    "keys": {
        # Not a record key: TimeSeries.metadata field read while the
        # scheduler BUILDS the chunk record's dms list (the reader
        # scope covers _run whole for its resume reads).
        "dm": "TimeSeries.metadata field, not a journal record key",
    },
    "kinds": {
        # Ledger rows' kind is the make_row(kind=...) ARGUMENT, set at
        # each call site ("survey"/"rseek"/"bench"/"stime") rather than
        # a dict literal the writer extraction can see. The scheduler's
        # full-replay resume filters ledger rows on it (PR 11: append
        # the row a killed predecessor never managed, exactly once).
        "survey": "ledger row kind passed dynamically via "
                  "ledger.maybe_append('survey', ...)",
    },
}


def _str_keys(dict_node):
    return [k.value for k in dict_node.keys
            if isinstance(k, ast.Constant) and isinstance(k.value, str)]


def _literal_kind(dict_node):
    for k, v in zip(dict_node.keys, dict_node.values):
        if isinstance(k, ast.Constant) and k.value == "kind" \
                and isinstance(v, ast.Constant) \
                and isinstance(v.value, str):
            return v.value
    return None


def _mentions_decomposition(node):
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and "decomposition" in sub.id:
            return True
        if isinstance(sub, ast.Attribute) \
                and "decomposition" in sub.attr:
            return True
    return False


class RecordSchemaAnalyzer(Analyzer):
    rule = "RIP010"
    name = "record-schema"
    description = ("every record key a reader consumes is emitted by a "
                   "writer, every consumed kind is emitted, and "
                   "decomposition-merged rows don't shadow "
                   "DECOMPOSITION_KEYS")

    def __init__(self, writers=None, readers=None, allowlist=None,
                 schema_rel=None):
        self.writers = WRITER_SPECS if writers is None else tuple(writers)
        self.readers = READER_SPECS if readers is None else tuple(readers)
        self.allowlist = (RECORD_ALLOWLIST if allowlist is None
                          else allowlist)
        self.schema_rel = SCHEMA_REL if schema_rel is None else schema_rel
        self._reset()

    def _reset(self):
        self._written = {}        # key -> {family}
        self._emitted_kinds = set()
        self._reads = []          # (ctx-like, node, key)
        self._kind_uses = []      # (ctx-like, node, kind literal)
        self._local_vocab = set()  # dict-literal keys inside reader funcs
        self._seen_writer = set()
        self._seen_reader = set()
        self._decomp_keys = None
        self._collision_findings = []

    def begin(self, repo):
        self._reset()

    # -- per-module extraction ----------------------------------------------

    def run(self, ctx):
        for rel, qual, family in self.writers:
            if rel == ctx.relpath:
                self._seen_writer.add((rel, qual))
                fn = self._function(ctx, qual)
                if fn is not None:
                    self._extract_writer(ctx, fn, family)
        for rel, qual in self.readers:
            if rel == ctx.relpath:
                self._seen_reader.add((rel, qual))
                self._extract_reader(ctx, qual)
        if ctx.relpath == self.schema_rel:
            self._decomp_keys = self._extract_decomp_keys(ctx)
        return []

    @staticmethod
    def _function(ctx, qual):
        for q, fn in walk_functions(ctx.tree):
            if q == qual:
                return fn
        return None

    def _extract_writer(self, ctx, fn, family):
        # Dict literals (by var when assigned), then subscript/
        # setdefault adds on the same vars.
        var_family = {}
        merged_decomp = set()
        literal_keys_of = {}   # var -> [first dict node, {literal keys}]

        def note(keys, fam, kind_literal):
            fam = kind_literal or fam
            if kind_literal:
                self._emitted_kinds.add(kind_literal)
            for k in keys:
                self._written.setdefault(k, set()).add(fam or "?")

        for sub in ast.walk(fn):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                    and isinstance(sub.targets[0], ast.Name) \
                    and isinstance(sub.value, ast.Dict):
                var = sub.targets[0].id
                kind = _literal_kind(sub.value)
                var_family[var] = kind or family
                keys = _str_keys(sub.value)
                literal_keys_of.setdefault(var, [sub.value, set()])[1] \
                    .update(keys)
                note(keys, family, kind)
            elif isinstance(sub, ast.Dict) and _str_keys(sub):
                note(_str_keys(sub), family, _literal_kind(sub))
            elif isinstance(sub, ast.Assign) \
                    and isinstance(sub.targets[0], ast.Subscript) \
                    and isinstance(sub.targets[0].value, ast.Name):
                var = sub.targets[0].value.id
                key = sub.targets[0].slice
                if isinstance(key, ast.Constant) \
                        and isinstance(key.value, str):
                    note([key.value], var_family.get(var, family), None)
            elif isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Attribute) \
                    and isinstance(sub.func.value, ast.Name):
                var = sub.func.value.id
                if sub.func.attr == "setdefault" and sub.args \
                        and isinstance(sub.args[0], ast.Constant) \
                        and isinstance(sub.args[0].value, str):
                    key = sub.args[0].value
                    kind = None
                    if key == "kind" and len(sub.args) > 1 \
                            and isinstance(sub.args[1], ast.Constant):
                        kind = sub.args[1].value
                        self._emitted_kinds.add(kind)
                    note([key], var_family.get(var, family), None)
                elif sub.func.attr == "update" and sub.args:
                    if isinstance(sub.args[0], ast.Dict):
                        note(_str_keys(sub.args[0]),
                             var_family.get(var, family),
                             _literal_kind(sub.args[0]))
                    elif _mentions_decomposition(sub.args[0]):
                        merged_decomp.add(var)

        # Collision check: a record merged with the run decomposition
        # must not literally name the decomposition's own keys.
        for var in sorted(merged_decomp):
            if var in literal_keys_of:
                node, keys = literal_keys_of[var]
                self._collision_findings.append((ctx, node, var,
                                                 set(keys)))

    def _extract_reader(self, ctx, qual):
        scopes = []
        if qual is None:
            scopes = [ctx.tree]
        else:
            fn = self._function(ctx, qual)
            if fn is not None:
                scopes = [fn]
        for scope in scopes:
            kind_vars = set()
            for sub in ast.walk(scope):
                # kind = rec.get("kind") one-step bindings.
                if isinstance(sub, ast.Assign) \
                        and len(sub.targets) == 1 \
                        and isinstance(sub.targets[0], ast.Name) \
                        and self._is_kind_access(sub.value):
                    kind_vars.add(sub.targets[0].id)
            for sub in ast.walk(scope):
                if isinstance(sub, ast.Dict):
                    self._local_vocab.update(_str_keys(sub))
                elif isinstance(sub, ast.Call) \
                        and isinstance(sub.func, ast.Attribute) \
                        and sub.func.attr in ("get", "setdefault") \
                        and sub.args \
                        and isinstance(sub.args[0], ast.Constant) \
                        and isinstance(sub.args[0].value, str):
                    self._reads.append((ctx, sub, sub.args[0].value))
                elif isinstance(sub, ast.Subscript) \
                        and isinstance(sub.slice, ast.Constant) \
                        and isinstance(sub.slice.value, str):
                    if isinstance(sub.ctx, ast.Load):
                        self._reads.append((ctx, sub, sub.slice.value))
                    else:
                        # A reader assembling its own structure
                        # (`report["trace"] = ...`) defines vocabulary,
                        # it does not consume a record key.
                        self._local_vocab.add(sub.slice.value)
                elif isinstance(sub, ast.Compare):
                    self._note_kind_compare(ctx, sub, kind_vars)

    @staticmethod
    def _is_kind_access(node):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "get" and node.args \
                and isinstance(node.args[0], ast.Constant) \
                and node.args[0].value == "kind":
            return True
        if isinstance(node, ast.Subscript) \
                and isinstance(node.slice, ast.Constant) \
                and node.slice.value == "kind":
            return True
        return False

    def _note_kind_compare(self, ctx, node, kind_vars):
        sides = [node.left] + list(node.comparators)
        is_kind = any(
            self._is_kind_access(s)
            or (isinstance(s, ast.Name) and s.id in kind_vars)
            for s in sides
        )
        if not is_kind:
            return
        for s in sides:
            if isinstance(s, ast.Constant) and isinstance(s.value, str):
                self._kind_uses.append((ctx, node, s.value))

    def _extract_decomp_keys(self, ctx):
        for node in ctx.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id == "DECOMPOSITION_KEYS" \
                    and isinstance(node.value, (ast.Tuple, ast.List)):
                return {e.value for e in node.value.elts
                        if isinstance(e, ast.Constant)}
        return None

    # -- the comparison -----------------------------------------------------

    def finalize(self, repo, contexts):
        findings = []

        # Spec staleness fails loudly (the HOT_FUNCTIONS discipline): a
        # renamed writer/reader must not silently unscope the check.
        known = {c.relpath for c in contexts}
        for rel, qual, _fam in self.writers:
            # Writers must live in the package: extraction only runs
            # over package contexts, so an out-of-package (or
            # vanished) writer spec covers nothing and must fail
            # loudly rather than quietly pass.
            if (rel, qual) in self._seen_writer \
                    and self._function_exists(contexts, rel, qual):
                continue
            findings.append(Finding(
                rel, 1, 0, self.rule,
                f"record writer {qual!r} not found in the package — "
                "the WRITER_SPECS list (analysis/record_schema.py) is "
                "stale; update it or the emission surface goes "
                "unchecked",
            ))
        for rel, qual in self.readers:
            if rel in known:
                if qual is None or self._function_exists(contexts, rel,
                                                         qual):
                    continue
            else:
                extra = self._load_extra(repo, rel)
                if extra is not None and (
                        qual is None
                        or any(q == qual
                               for q, _ in walk_functions(extra.tree))):
                    self._extract_reader(extra, qual)
                    continue
            findings.append(Finding(
                rel, 1, 0, self.rule,
                f"record reader {qual or '<module>'!r} not found — the "
                "READER_SPECS list (analysis/record_schema.py) is "
                "stale; update it or the consumption surface goes "
                "unchecked",
            ))

        written = set(self._written)
        allow_keys = set(self.allowlist.get("keys", ()))
        allow_kinds = set(self.allowlist.get("kinds", ()))
        seen = set()
        for ctx, node, key in self._reads:
            if key in written or key in self._local_vocab \
                    or key in allow_keys:
                continue
            loc = (ctx.relpath, getattr(node, "lineno", 1), key)
            if loc in seen:
                continue
            seen.add(loc)
            findings.append(Finding.at(
                ctx, node, self.rule,
                f"record key {key!r} is read here but no writer emits "
                "it — a renamed or dropped writer key turns this read "
                "into its .get() default forever (fix the writer, or "
                "allowlist the documented backward-compat read in "
                "analysis/record_schema.py RECORD_ALLOWLIST)",
            ))
        for ctx, node, kind in self._kind_uses:
            if kind in self._emitted_kinds or kind in allow_kinds:
                continue
            loc = (ctx.relpath, getattr(node, "lineno", 1), kind)
            if loc in seen:
                continue
            seen.add(loc)
            findings.append(Finding.at(
                ctx, node, self.rule,
                f"record kind {kind!r} is consumed here but no writer "
                "emits it — this filter matches nothing (fix the kind "
                "string, or allowlist it in RECORD_ALLOWLIST)",
            ))
        if self._decomp_keys:
            for ctx, node, var, keys in self._collision_findings:
                clash = sorted(keys & self._decomp_keys)
                if clash:
                    findings.append(Finding.at(
                        ctx, node, self.rule,
                        f"record dict `{var}` names decomposition "
                        f"key(s) {clash} AND merges the run "
                        "decomposition over itself — one side silently "
                        "clobbers the other; drop the literal key(s)",
                    ))
        findings.sort(key=lambda f: (f.path, f.line, f.col))
        return findings

    @staticmethod
    def _function_exists(contexts, rel, qual):
        for c in contexts:
            if c.relpath == rel:
                return any(q == qual for q, _ in walk_functions(c.tree))
        return False

    def _load_extra(self, repo, rel):
        """Parse a spec'd file outside the package (tools/ readers);
        writer extraction from it is not supported — readers only."""
        path = os.path.join(repo, rel)
        if not os.path.exists(path):
            return None
        try:
            return ModuleContext(repo, rel)
        except (OSError, SyntaxError):
            return None
