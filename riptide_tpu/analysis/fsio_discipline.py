"""
RIP013 — fsio write discipline in the persistence-bearing planes.

PR 11 routed every durable artifact (journal, peaks, ledger,
heartbeats, status sidecars) through ``utils/fsio.py`` — fsync'd
atomic replace, CRC framing, torn-tail healing — and the chaos
campaign proves byte-identical recovery through kills at every
persistence site. A direct ``open(..., "w")``/``os.replace``/
``os.write`` added later to survey/obs/serve quietly re-opens the
torn-write window the whole layer exists to close, and nothing fails
until a kill lands in it. This rule pins the discipline: inside
``riptide_tpu/{survey,obs,serve}/`` every raw write-mode ``open``
(mode literal containing ``w``/``a``/``x``), ``os.replace`` and
``os.write`` is a finding. ``utils/fsio.py`` itself lives outside
the scoped planes; ``survey/chaos.py`` is exempt by construction —
the fault-injection harness deliberately writes raw and torn bytes
to prove the readers heal them.
"""
import ast

from .core import Analyzer, Finding, dotted

__all__ = ["FsioDisciplineAnalyzer", "SCOPE_PREFIXES", "EXEMPT"]

SCOPE_PREFIXES = ("riptide_tpu/survey/", "riptide_tpu/obs/",
                  "riptide_tpu/serve/")
# The chaos harness writes raw/truncated/corrupt bytes ON PURPOSE —
# its whole job is producing the torn artifacts fsio must survive.
EXEMPT = ("riptide_tpu/survey/chaos.py",)

_WRITE_MODES = frozenset("wax")


def _write_mode_literal(call):
    """The mode string of an ``open``/``io.open`` call when it is a
    literal selecting a write mode, else None (a non-literal mode is
    not flagged — conservative, like the rest of the framework)."""
    mode = None
    if len(call.args) >= 2:
        mode = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str) \
            and (_WRITE_MODES & set(mode.value)):
        return mode.value
    return None


class FsioDisciplineAnalyzer(Analyzer):
    rule = "RIP013"
    name = "fsio-discipline"
    description = ("survey/obs/serve write durable bytes only through "
                   "utils/fsio.py — no raw write-mode open(), "
                   "os.replace or os.write in the persistence planes")

    def run(self, ctx):
        if not ctx.relpath.startswith(SCOPE_PREFIXES) \
                or ctx.relpath in EXEMPT:
            return []
        findings = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func)
            if name in ("open", "io.open"):
                mode = _write_mode_literal(node)
                if mode is not None:
                    findings.append(Finding.at(
                        ctx, node, self.rule,
                        f"raw open(..., {mode!r}) in the persistence "
                        "plane — route through utils/fsio.py "
                        "(atomic_write_text/atomic_write_bytes/"
                        "append_framed) so a kill cannot tear the "
                        "artifact"))
            elif name in ("os.replace", "os.write"):
                findings.append(Finding.at(
                    ctx, node, self.rule,
                    f"raw {name}() in the persistence plane — "
                    "utils/fsio.py owns replace/fd writes (fsync "
                    "ordering, CRC framing); call its helpers "
                    "instead"))
        findings.sort(key=lambda f: (f.path, f.line, f.col))
        return findings
