"""
RIP005 — Pallas kernel layout discipline.

Mosaic kernels fail in uniquely unpleasant ways when their launch
geometry is sloppy: a dynamic shape reaching a ``BlockSpec`` or
``grid`` retraces (or miscompiles) per call; an implicit memory space
lets a scratch land in the wrong one silently; Python-side
nondeterminism (time, random, environment) captured into a kernel
closure bakes an unreproducible constant into a cached executable —
the exact failure class KERNEL_CACHE_VERSION exists to prevent.

Scoped to modules that import ``jax.experimental.pallas``. Checks:

* every ``pl.BlockSpec(...)`` names ``memory_space=`` explicitly;
* every ``pl.pallas_call(...)`` passes ``out_shape=`` and a ``grid=``
  or ``grid_spec=``;
* shape positions (``BlockSpec`` block shapes, ``grid=`` tuples —
  including inside a ``grid_spec=PrefetchScalarGridSpec(...)``) hold
  static expressions: names, constants and arithmetic only, no calls;
* kernel bodies (the function handed to ``pallas_call``, plus every
  module function reachable from it) are free of host nondeterminism:
  ``time.*``, ``random.*``, ``np.random.*``, ``os.environ`` /
  ``os.getenv``, ``hash()``, ``id()``, ``datetime.*``.
"""
import ast

from .core import Analyzer, Finding, dotted, walk_functions

__all__ = ["PallasLayoutAnalyzer"]

_NONDET_PREFIXES = ("time.", "random.", "np.random.", "numpy.random.",
                    "datetime.", "os.environ", "os.getenv")
_NONDET_BARE = {"hash", "id", "getenv"}


def _imports_pallas(tree):
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            if node.module and "pallas" in node.module:
                return True
            if any("pallas" in a.name for a in node.names):
                return True
        elif isinstance(node, ast.Import):
            if any("pallas" in a.name for a in node.names):
                return True
    return False


def _calls_in_shape(node):
    """Call nodes appearing inside a shape/grid expression (dynamic
    geometry), ignoring lambdas (index maps are callables by
    contract)."""
    out = []
    for sub in ast.walk(node):
        if isinstance(sub, ast.Lambda):
            return out  # index map: its body is not a shape
        if isinstance(sub, ast.Call):
            out.append(sub)
    return out


def _kw(call, name):
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


class PallasLayoutAnalyzer(Analyzer):
    rule = "RIP005"
    name = "pallas-layout"
    description = ("static BlockSpec/grid shapes, explicit memory "
                   "spaces, and nondeterminism-free kernel closures in "
                   "Pallas modules")

    def run(self, ctx):
        if not _imports_pallas(ctx.tree):
            return []
        findings = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func) or ""
            leaf = name.split(".")[-1]
            if leaf == "BlockSpec":
                if _kw(node, "memory_space") is None:
                    findings.append(Finding.at(
                        ctx, node, self.rule,
                        "`BlockSpec` without an explicit `memory_space=` "
                        "— where a block lives (VMEM/SMEM/ANY) is part "
                        "of the kernel contract, not a default",
                    ))
                for pos in node.args[:1]:  # block shape
                    for call in _calls_in_shape(pos):
                        findings.append(Finding.at(
                            ctx, call, self.rule,
                            "dynamic expression in a `BlockSpec` block "
                            "shape — shapes must be static (hoist the "
                            "value into a build-key parameter)",
                        ))
            elif leaf == "pallas_call":
                if _kw(node, "out_shape") is None:
                    findings.append(Finding.at(
                        ctx, node, self.rule,
                        "`pallas_call` without `out_shape=` — output "
                        "geometry must be explicit",
                    ))
                grid = _kw(node, "grid")
                grid_spec = _kw(node, "grid_spec")
                if grid is None and grid_spec is None:
                    findings.append(Finding.at(
                        ctx, node, self.rule,
                        "`pallas_call` without `grid=`/`grid_spec=` — "
                        "launch geometry must be explicit",
                    ))
                if grid is not None:
                    for call in _calls_in_shape(grid):
                        findings.append(Finding.at(
                            ctx, call, self.rule,
                            "dynamic expression in `grid=` — the launch "
                            "grid must be static",
                        ))
            elif leaf in ("PrefetchScalarGridSpec", "GridSpec"):
                grid = _kw(node, "grid")
                if grid is not None:
                    for call in _calls_in_shape(grid):
                        findings.append(Finding.at(
                            ctx, call, self.rule,
                            "dynamic expression in a grid spec's "
                            "`grid=` — the launch grid must be static",
                        ))
        findings.extend(self._check_kernel_closures(ctx))
        return findings

    # -- nondeterminism in kernel closures ------------------------------

    def _kernel_roots(self, ctx):
        """Names of functions handed to pallas_call (directly, or as
        the first argument of a functools.partial bound to the variable
        passed in)."""
        roots = set()
        partials = {}  # var name -> partial'd function name
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign) and isinstance(node.value,
                                                           ast.Call):
                cname = dotted(node.value.func) or ""
                if cname.split(".")[-1] == "partial" and node.value.args:
                    inner = dotted(node.value.args[0])
                    if inner and len(node.targets) == 1 \
                            and isinstance(node.targets[0], ast.Name):
                        partials[node.targets[0].id] = inner
            if isinstance(node, ast.Call):
                name = dotted(node.func) or ""
                if name.split(".")[-1] == "pallas_call" and node.args:
                    a = node.args[0]
                    if isinstance(a, ast.Name):
                        roots.add(partials.get(a.id, a.id))
                    elif isinstance(a, ast.Call):
                        cname = dotted(a.func) or ""
                        if cname.split(".")[-1] == "partial" and a.args:
                            inner = dotted(a.args[0])
                            if inner:
                                roots.add(inner)
        return roots

    def _check_kernel_closures(self, ctx):
        functions = dict(walk_functions(ctx.tree))
        by_leaf = {}
        for qual, fn in functions.items():
            by_leaf.setdefault(qual.split(".")[-1], fn)
        # Transitive closure over module-level function calls.
        reach = set()
        frontier = [r for r in self._kernel_roots(ctx) if r in by_leaf]
        while frontier:
            name = frontier.pop()
            if name in reach:
                continue
            reach.add(name)
            for node in ast.walk(by_leaf[name]):
                if isinstance(node, ast.Call):
                    callee = (dotted(node.func) or "").split(".")[-1]
                    if callee in by_leaf and callee not in reach:
                        frontier.append(callee)
        findings = []
        seen = set()
        for name in sorted(reach):
            for node in ast.walk(by_leaf[name]):
                loc = (getattr(node, "lineno", 0),
                       getattr(node, "col_offset", 0))
                if loc in seen:
                    continue
                expr = dotted(node) if isinstance(node,
                                                  ast.Attribute) else None
                if isinstance(node, ast.Call):
                    cname = dotted(node.func) or ""
                    bad = (any(cname.startswith(p)
                               for p in _NONDET_PREFIXES)
                           or cname in _NONDET_BARE)
                    if bad:
                        seen.add(loc)
                        findings.append(Finding.at(
                            ctx, node, self.rule,
                            f"host nondeterminism (`{cname}`) inside "
                            f"kernel closure `{name}` — a cached "
                            "executable would bake this value in "
                            "(KERNEL_CACHE_VERSION cannot see it)",
                        ))
                elif expr and any(expr.startswith(p)
                                  for p in ("os.environ", "time.",
                                            "random.")):
                    seen.add(loc)
                    findings.append(Finding.at(
                        ctx, node, self.rule,
                        f"host state read (`{expr}`) inside kernel "
                        f"closure `{name}` — kernel bodies must be pure "
                        "functions of their operands and static "
                        "parameters",
                    ))
        return findings
