"""
riplint: the shared static-analysis framework.

A single AST walk over the package feeds the per-module analyzers, and
one shared :class:`~riptide_tpu.analysis.core.ProjectContext` (a
name-resolved whole-program call graph: imports, self-attribute types,
thread targets) feeds the interprocedural ones. Each analyzer owns one
stable rule id (asserted by tests/test_riplint.py):

========  ==========================  =====================================
RIP001    host-sync                   no host synchronisation (`.item()`,
                                      `block_until_ready`, numpy pulls)
                                      inside jit-traced bodies or the
                                      engine/batcher queueing hot paths
RIP002    dtype-discipline            float64 accumulator rule + explicit
                                      dtypes in ops/ and the kernel paths
RIP003    env-flags                   every RIPTIDE_* read routes through
                                      the typed utils/envflags.py registry
                                      (stale entries + docs drift checked)
RIP004    lock-discipline             no blocking call while holding a
                                      lock, no untimed join()/wait(),
                                      explicit Thread daemon flags
RIP005    pallas-layout               static BlockSpec/grid shapes,
                                      explicit memory spaces, no host
                                      nondeterminism in kernel closures
RIP006    finite-guards               data entry points route through the
                                      quality layer (ported from
                                      tools/check_finite_guards.py)
RIP007    liveness-guards             multihost_utils collectives route
                                      through the bounded-wait wrappers
                                      (ported from
                                      tools/check_liveness_guards.py)
RIP008    obs-discipline              span() only as a context manager,
                                      no tracing inside jit bodies or
                                      Pallas kernel closures, and every
                                      RIPTIDE_TRACE_*/RIPTIDE_PROM_* flag
                                      registered in envflags.py
RIP009    lock-order                  whole-program lock-acquisition-
                                      order cycles (held-lock sets
                                      propagated through the call graph)
                                      and lock-free writes to attributes
                                      guarded elsewhere
RIP010    record-schema               journal/ledger/incident record keys
                                      and kinds a reader consumes are
                                      emitted by a writer; decomposition-
                                      merged rows don't shadow
                                      DECOMPOSITION_KEYS
RIP011    interp-host-sync            RIP001 lifted to call-graph
                                      reachability: sync pulls hidden in
                                      helpers called from jit bodies or
                                      Pallas kernel closures
RIP012    runctx-discipline           threads spawned from the serve/
                                      survey planes carry a run context
                                      (runctx.wrap-ed target or one that
                                      installs its own), and no
                                      context-free thread reaches
                                      incidents.emit
RIP013    fsio-discipline             survey/obs/serve write durable
                                      bytes only through utils/fsio.py
                                      (no raw write-mode open(),
                                      os.replace, os.write)
RIP014    gate-pairing                chunk_gate begin/end, StagingPool
                                      acquire/release and integrity
                                      begin_fold/finish_fold pair on
                                      every path (try/finally, with, or
                                      ownership escape)
========  ==========================  =====================================

Run via ``tools/riplint.py`` (GitHub-annotation output, checked-in
baseline with per-entry justifications, ``# riplint: disable=RIPxxx``
inline suppressions). This package must stay importable WITHOUT jax —
the runner loads it standalone by file path so ``make check`` needs no
backend.
"""
from .core import (  # noqa: F401
    Analyzer, Baseline, Finding, FunctionInfo, ModuleContext,
    ProjectContext, collect_contexts, run_analyzers,
)
from .host_sync import HostSyncAnalyzer
from .dtype_discipline import DtypeDisciplineAnalyzer
from .env_flags import EnvFlagAnalyzer
from .lock_discipline import LockDisciplineAnalyzer
from .pallas_layout import PallasLayoutAnalyzer
from .finite_guards import FiniteGuardAnalyzer
from .liveness_guards import LivenessGuardAnalyzer
from .obs_discipline import ObsDisciplineAnalyzer
from .lock_order import LockOrderAnalyzer
from .record_schema import RecordSchemaAnalyzer
from .interp_host_sync import InterpHostSyncAnalyzer
from .runctx_discipline import RunctxDisciplineAnalyzer
from .fsio_discipline import FsioDisciplineAnalyzer
from .gate_pairing import GatePairingAnalyzer

ALL_ANALYZERS = (
    HostSyncAnalyzer,
    DtypeDisciplineAnalyzer,
    EnvFlagAnalyzer,
    LockDisciplineAnalyzer,
    PallasLayoutAnalyzer,
    FiniteGuardAnalyzer,
    LivenessGuardAnalyzer,
    ObsDisciplineAnalyzer,
    LockOrderAnalyzer,
    RecordSchemaAnalyzer,
    InterpHostSyncAnalyzer,
    RunctxDisciplineAnalyzer,
    FsioDisciplineAnalyzer,
    GatePairingAnalyzer,
)

__all__ = [
    "ALL_ANALYZERS", "Analyzer", "Baseline", "Finding", "FunctionInfo",
    "ModuleContext", "ProjectContext", "collect_contexts",
    "run_analyzers",
] + [a.__name__ for a in ALL_ANALYZERS]
