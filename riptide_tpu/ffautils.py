"""Width-trial ladder generation (reference: riptide/ffautils.py)."""
from .ops.reference import generate_width_trials

__all__ = ["generate_width_trials"]
